"""Grouped segmented prefix scan — the TPU replacement for the reference's
per-event HashMap group-by (core/query/selector/QuerySelector.java:207,
GroupByKeyGenerator.java:37 string-concat keys + per-key AggregatorState).

Semantics to reproduce: events are processed one at a time; each CURRENT lane
adds its delta to the per-key accumulator and the *post-update* value is
emitted for that lane; EXPIRED lanes subtract (window removal); RESET lanes
zero the accumulator (batch windows). Batched faithfully as:

  1. each lane carries (slot, delta, sign) — slot is a dense int32 key id
  2. lanes are stably sorted by slot; signed deltas are prefix-summed within
     each slot segment; carry-in comes from the persistent state table
  3. results scatter back to original lane order; segment totals update state

RESET is handled with *epochs*: a per-key epoch counter increments on reset;
a state-table value whose epoch is stale reads as the aggregator's zero. This
keeps the scan a pure prefix-sum (no data-dependent control flow, XLA-friendly).

All arrays are fixed-shape; invalid lanes carry slot = capacity sentinel so they
sort to the end and never touch real segments.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


def invert_permutation(perm: jax.Array) -> jax.Array:
    """Inverse of a permutation via scatter — O(n), vs the O(n log n) second
    sort of the argsort(argsort(x)) idiom (slow on TPU)."""
    n = perm.shape[0]
    return jnp.zeros((n,), perm.dtype).at[perm].set(
        jnp.arange(n, dtype=perm.dtype))


class GroupState(NamedTuple):
    """Persistent per-key accumulator table (one per aggregator component).

    values: [K] accumulator per key slot
    epoch:  [K] int32 epoch of last write; values with epoch < current read as 0
    """

    values: jax.Array
    epoch: jax.Array


def init_group_state(capacity: int, dtype) -> GroupState:
    return GroupState(
        values=jnp.zeros((capacity,), dtype=dtype),
        epoch=jnp.zeros((capacity,), dtype=jnp.int32),
    )


def grouped_scan(
    state: GroupState,
    slots: jax.Array,  # int32[L] dense key ids; invalid lanes = any value
    deltas: jax.Array,  # [L] signed per-lane contribution (already sign-applied)
    valid: jax.Array,  # bool[L]
    resets: jax.Array,  # bool[L] lanes that zero their key's accumulator first
    current_epoch: jax.Array,  # int32 scalar epoch counter (increments per reset batch)
    op: str = "sum",  # "sum" | "min" | "max"
) -> tuple[GroupState, jax.Array]:
    """Returns (new_state, per-lane post-update accumulator values).

    `current_epoch` must be >= max(state.epoch); reset lanes bump the epoch of
    *all* keys (batch-window RESET clears every group, matching the reference's
    QuerySelector RESET pass). Keys untouched after a reset read as zero via
    epoch mismatch — no O(K) clear.

    op="min"/"max" support monotone aggregators (no EXPIRED removal — the
    planner forbids min/max over sliding windows until the segment-tree ring
    lands); identity is +/-inf (or dtype extremes for ints).
    """
    K = state.values.shape[0]
    plan = _segment_plan(slots, valid, resets, current_epoch, K)
    new_values, s_out = _scan_component(
        state.values, state.epoch, deltas, valid, plan, op)
    new_epoch = state.epoch.at[plan.write_slot].set(
        plan.s_epochs.astype(state.epoch.dtype), mode="drop")
    return GroupState(new_values, new_epoch), s_out[plan.inv]


class _SegmentPlan(NamedTuple):
    """Shared per-batch segment structure: one sort + boundary computation
    reused by every component scanned over the same (slots, valid, resets)."""

    order: jax.Array
    inv: jax.Array
    s_slots: jax.Array
    s_epochs: jax.Array
    seg_start: jax.Array
    safe_slots: jax.Array
    epoch_ok_slots: jax.Array  # s_slots < K (validity of gathers)
    write_slot: jax.Array


def _segment_plan(slots, valid, resets, current_epoch, K) -> _SegmentPlan:
    sentinel = jnp.int32(K)
    slots_v = jnp.where(valid, slots, sentinel)

    # epoch id per lane: lanes after the r-th reset belong to epoch
    # current_epoch + r. cumsum of resets gives r per lane (reset lane itself
    # starts the new epoch).
    reset_rank = jnp.cumsum(resets.astype(jnp.int32))
    lane_epoch = current_epoch + reset_rank

    # stable sort by (slot, lane) — lane order inside a slot is preserved
    order = jnp.argsort(slots_v, stable=True)
    inv = invert_permutation(order)
    s_slots = slots_v[order]
    s_epochs = lane_epoch[order]

    # a new segment starts when slot changes OR lane epoch changes
    prev_slot = jnp.concatenate([jnp.full((1,), -1, s_slots.dtype), s_slots[:-1]])
    prev_epoch = jnp.concatenate([jnp.full((1,), -1, s_epochs.dtype), s_epochs[:-1]])
    seg_start = (s_slots != prev_slot) | (s_epochs != prev_epoch)

    safe_slots = jnp.minimum(s_slots, K - 1)

    # state writes come from the last lane of each *slot* run (unique per
    # slot, so the scatter has no duplicate indices; last epoch's value wins)
    next_slot = jnp.concatenate([s_slots[1:], jnp.full((1,), -1, s_slots.dtype)])
    is_slot_end = s_slots != next_slot
    write_slot = jnp.where((s_slots < K) & is_slot_end, s_slots, sentinel)

    return _SegmentPlan(order, inv, s_slots, s_epochs, seg_start, safe_slots,
                        s_slots < K, write_slot)


def _scan_component(values, epoch_table, deltas, valid, plan: _SegmentPlan,
                    op: str):
    """One component's segmented scan + carry + state write over a shared
    plan. Returns (new_values, sorted-order outputs)."""
    combine, identity = _OPS[op](deltas.dtype)
    s_deltas = jnp.where(valid, deltas,
                         jnp.full_like(deltas, identity))[plan.order]
    within = _segmented_scan(s_deltas, plan.seg_start, combine, identity)

    # carry-in: only the segment whose epoch matches the state's stored epoch
    # for that slot gets the stored value; stale epochs read the identity.
    stored_vals = values[plan.safe_slots]
    stored_epoch = epoch_table[plan.safe_slots]
    carry = jnp.where(
        plan.epoch_ok_slots & (stored_epoch == plan.s_epochs), stored_vals,
        jnp.full_like(stored_vals, identity))
    carry_at_start = jnp.where(plan.seg_start, carry,
                               jnp.full_like(carry, identity))
    carry_seg = _segment_broadcast_op(carry_at_start, plan.seg_start, identity)

    s_out = combine(carry_seg, within)
    new_values = values.at[plan.write_slot].set(
        s_out.astype(values.dtype), mode="drop")
    return new_values, s_out


def grouped_scan_fused(
    values_list: list,  # per component: [K] accumulator array
    shared_epoch: jax.Array,  # int32[K] — ONE epoch table for all components
    slots: jax.Array,
    deltas_list: list,  # per component: [L] signed deltas
    valid: jax.Array,
    resets: jax.Array,
    current_epoch: jax.Array,
) -> tuple[list, jax.Array, list]:
    """grouped_scan for N sum-op components sharing (slots, valid, resets):
    ONE sort, ONE segment structure, ONE epoch gather/scatter — instead of N
    of each. The dominant per-step HBM traffic for multi-aggregate queries
    (sum+avg = 3 components) drops accordingly. Semantics identical to N
    grouped_scan(op='sum') calls.

    Returns (new_values_list, new_shared_epoch, per-lane outputs list)."""
    K = shared_epoch.shape[0]
    plan = _segment_plan(slots, valid, resets, current_epoch, K)
    new_values, outs = [], []
    for values, deltas in zip(values_list, deltas_list):
        nv, s_out = _scan_component(values, shared_epoch, deltas, valid, plan,
                                    "sum")
        new_values.append(nv)
        outs.append(s_out[plan.inv])
    new_epoch = shared_epoch.at[plan.write_slot].set(
        plan.s_epochs.astype(shared_epoch.dtype), mode="drop")
    return new_values, new_epoch, outs


def _op_sum(dtype):
    if dtype == jnp.bool_:
        return jnp.logical_or, False
    return jnp.add, jnp.zeros((), dtype)


def _op_min(dtype):
    ident = jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer) else jnp.inf
    return jnp.minimum, jnp.asarray(ident, dtype)


def _op_max(dtype):
    ident = jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer) else -jnp.inf
    return jnp.maximum, jnp.asarray(ident, dtype)


_OPS = {"sum": _op_sum, "min": _op_min, "max": _op_max}


def _segmented_scan(vals: jax.Array, seg_start: jax.Array, combine, identity) -> jax.Array:
    """Inclusive scan that restarts at each segment start (classic conditional
    associative scan: carry a (reset_flag, value) pair)."""

    def op(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, combine(av, bv))

    flags = seg_start
    _, out = jax.lax.associative_scan(op, (flags, vals))
    return out


def _segment_broadcast_op(vals_at_start: jax.Array, seg_start: jax.Array, identity) -> jax.Array:
    """Broadcast each segment-start value across its segment."""
    L = seg_start.shape[0]
    idx = jnp.arange(L)
    start_idx = jnp.where(seg_start, idx, 0)
    start_idx = jax.lax.associative_scan(jnp.maximum, start_idx)
    return vals_at_start[start_idx]


# --- device-side key table ------------------------------------------------------


class KeyTable(NamedTuple):
    """Append-only device dictionary: 64-bit composite keys → dense int32 ids.

    Replaces the reference's string-concat HashMap group-by keys
    (GroupByKeyGenerator.java:37) for non-string keys, fully on device: lookup
    is a binary search over a sorted copy; inserts merge the batch's new unique
    keys and re-sort. Ids are assigned in order of first appearance.
    """

    sorted_keys: jax.Array  # int64[K], padded with INT64_MAX
    sorted_ids: jax.Array  # int32[K]
    count: jax.Array  # int32 number of live keys


_KEY_PAD = jnp.iinfo(jnp.int64).max


def init_key_table(capacity: int) -> KeyTable:
    return KeyTable(
        sorted_keys=jnp.full((capacity,), _KEY_PAD, dtype=jnp.int64),
        sorted_ids=jnp.zeros((capacity,), dtype=jnp.int32),
        count=jnp.int32(0),
    )


def key_lookup_or_insert(
    table: KeyTable, keys: jax.Array, valid: jax.Array
) -> tuple[KeyTable, jax.Array]:
    """Resolve each lane's key to a dense id, inserting unseen keys.

    Returns (new_table, ids[L]). Invalid lanes get id 0 (callers mask them).
    Overflow beyond capacity silently reuses id 0 — callers size K generously
    and monitor table.count.
    """
    L = keys.shape[0]
    K = table.sorted_keys.shape[0]
    keys = keys.astype(jnp.int64)
    # avoid colliding with the pad sentinel
    keys = jnp.where(keys == _KEY_PAD, _KEY_PAD - 1, keys)

    pos = jnp.searchsorted(table.sorted_keys, keys)
    pos_c = jnp.clip(pos, 0, K - 1)
    found = table.sorted_keys[pos_c] == keys
    existing_ids = table.sorted_ids[pos_c]

    # identify first occurrence of each new key within the batch, in lane order
    is_new = valid & ~found
    nk = jnp.where(is_new, keys, _KEY_PAD)
    order = jnp.argsort(nk, stable=True)  # groups duplicates, keeps lane order
    snk = nk[order]
    first = jnp.concatenate([jnp.ones((1,), bool), snk[1:] != snk[:-1]]) & (snk != _KEY_PAD)
    # rank new unique keys by first-appearance lane index for deterministic ids
    first_lane = jnp.where(first, order, L)
    lane_rank = invert_permutation(jnp.argsort(first_lane, stable=True))
    new_id_sorted = table.count + lane_rank.astype(jnp.int32)

    # each lane's id: for new keys, find their unique-key id via the sorted run
    run_id = _segment_broadcast_op(
        jnp.where(first, new_id_sorted, 0), first | (snk == _KEY_PAD), 0)
    lane_new_ids = jnp.zeros((L,), jnp.int32).at[order].set(
        jnp.where(snk != _KEY_PAD, run_id, 0).astype(jnp.int32))

    ids = jnp.where(found, existing_ids, lane_new_ids)
    ids = jnp.where(valid, ids, 0)

    # merge new unique keys into the sorted table
    n_new = jnp.sum(first.astype(jnp.int32))
    merged_keys = jnp.concatenate([table.sorted_keys,
                                   jnp.where(first, snk, _KEY_PAD)])
    merged_ids = jnp.concatenate([table.sorted_ids,
                                  jnp.where(first, new_id_sorted, 0)])
    morder = jnp.argsort(merged_keys, stable=True)[:K]
    new_table = KeyTable(
        sorted_keys=merged_keys[morder],
        sorted_ids=merged_ids[morder],
        count=jnp.minimum(table.count + n_new, K),
    )
    return new_table, ids


def hash_columns(cols: list[jax.Array]) -> jax.Array:
    """Combine multiple key columns into one int64 key (fxhash-style mix).
    Collision probability over 64 bits is negligible for CEP key cardinalities.
    Float columns hash by BIT PATTERN (like Java's Double.hashCode), not by
    int truncation — 1.2 and 1.9 are distinct keys."""
    h = jnp.uint64(0xCBF29CE484222325)
    for c in cols:
        if jnp.issubdtype(c.dtype, jnp.floating):
            bits = jax.lax.bitcast_convert_type(
                c, jnp.int32 if c.dtype == jnp.float32 else jnp.int64)
            x = bits.astype(jnp.int64).astype(jnp.uint64)
        else:
            x = c.astype(jnp.int64).astype(jnp.uint64)
        h = (h ^ x) * jnp.uint64(0x100000001B3)
        h = h ^ (h >> 29)
    return h.astype(jnp.int64)


# --- host-side key dictionaries -------------------------------------------------


class KeyDictionary:
    """Host-side composite-key → dense slot assignment for group-by keys that are
    not already dense codes. Append-only; snapshot/restorable. The TPU analogue
    of the reference's group-by key strings: here a key becomes one int32 the
    device can scatter with."""

    def __init__(self) -> None:
        self._map: dict[tuple, int] = {}

    def assign(self, keys) -> "list[int]":
        out = []
        m = self._map
        for k in keys:
            slot = m.get(k)
            if slot is None:
                slot = len(m)
                m[k] = slot
            out.append(slot)
        return out

    def __len__(self) -> int:
        return len(self._map)

    def snapshot(self) -> list:
        return sorted(self._map.items(), key=lambda kv: kv[1])

    def restore(self, items) -> None:
        self._map = {tuple(k) if isinstance(k, list) else k: v for k, v in items}
