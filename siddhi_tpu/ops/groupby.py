"""Grouped segmented prefix scan — the TPU replacement for the reference's
per-event HashMap group-by (core/query/selector/QuerySelector.java:207,
GroupByKeyGenerator.java:37 string-concat keys + per-key AggregatorState).

Semantics to reproduce: events are processed one at a time; each CURRENT lane
adds its delta to the per-key accumulator and the *post-update* value is
emitted for that lane; EXPIRED lanes subtract (window removal); RESET lanes
zero the accumulator (batch windows). Batched faithfully as:

  1. each lane carries (slot, delta, sign) — slot is a dense int32 key id
  2. lanes are stably sorted by slot; signed deltas are prefix-summed within
     each slot segment; carry-in comes from the persistent state table
  3. results scatter back to original lane order; segment totals update state

RESET is handled with *epochs*: a per-key epoch counter increments on reset;
a state-table value whose epoch is stale reads as the aggregator's zero. This
keeps the scan a pure prefix-sum (no data-dependent control flow, XLA-friendly).

All arrays are fixed-shape; invalid lanes carry slot = capacity sentinel so they
sort to the end and never touch real segments.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .search import searchsorted32, stable_argsort_bounded


def invert_permutation(perm: jax.Array) -> jax.Array:
    """Inverse of a permutation via scatter — O(n), vs the O(n log n) second
    sort of the argsort(argsort(x)) idiom (slow on TPU)."""
    n = perm.shape[0]
    return jnp.zeros((n,), perm.dtype).at[perm].set(
        jnp.arange(n, dtype=perm.dtype))


class GroupState(NamedTuple):
    """Persistent per-key accumulator table (one per aggregator component).

    values: [K] accumulator per key slot
    epoch:  [K] int32 epoch of last write; values with epoch < current read as 0
    """

    values: jax.Array
    epoch: jax.Array


def init_group_state(capacity: int, dtype) -> GroupState:
    return GroupState(
        values=jnp.zeros((capacity,), dtype=dtype),
        epoch=jnp.zeros((capacity,), dtype=jnp.int32),
    )


def grouped_scan(
    state: GroupState,
    slots: jax.Array,  # int32[L] dense key ids; invalid lanes = any value
    deltas: jax.Array,  # [L] signed per-lane contribution (already sign-applied)
    valid: jax.Array,  # bool[L]
    resets: jax.Array,  # bool[L] lanes that zero their key's accumulator first
    current_epoch: jax.Array,  # int32 scalar epoch counter (increments per reset batch)
    op: str = "sum",  # "sum" | "min" | "max"
) -> tuple[GroupState, jax.Array]:
    """Returns (new_state, per-lane post-update accumulator values).

    `current_epoch` must be >= max(state.epoch); reset lanes bump the epoch of
    *all* keys (batch-window RESET clears every group, matching the reference's
    QuerySelector RESET pass). Keys untouched after a reset read as zero via
    epoch mismatch — no O(K) clear.

    op="min"/"max" support monotone aggregators (no EXPIRED removal — the
    planner forbids min/max over sliding windows until the segment-tree ring
    lands); identity is +/-inf (or dtype extremes for ints).
    """
    K = state.values.shape[0]
    plan = _segment_plan(slots, valid, resets, current_epoch, K)
    new_values, s_out = _scan_component(
        state.values, state.epoch, deltas, valid, plan, op)
    new_epoch = state.epoch.at[plan.write_slot].set(
        plan.s_epochs.astype(state.epoch.dtype), mode="drop")
    # scatter back to lane order (one scatter; an inverse-permutation gather
    # would cost an extra scatter to build the inverse)
    out = jnp.zeros_like(s_out).at[plan.order].set(s_out)
    return GroupState(new_values, new_epoch), out


class _SegmentPlan(NamedTuple):
    """Shared per-batch segment structure: one sort + boundary computation
    reused by every component scanned over the same (slots, valid, resets)."""

    order: jax.Array
    s_slots: jax.Array
    s_epochs: jax.Array
    seg_start: jax.Array
    safe_slots: jax.Array
    epoch_ok_slots: jax.Array  # s_slots < K (validity of gathers)
    write_slot: jax.Array
    #: index of each lane's segment start (shared max-scan — carry
    #: broadcasts become gathers instead of one assoc-scan per component)
    start_idx: jax.Array


def _segment_plan(slots, valid, resets, current_epoch, K) -> _SegmentPlan:
    sentinel = jnp.int32(K)
    slots_v = jnp.where(valid, slots, sentinel)

    # epoch id per lane: lanes after the r-th reset belong to epoch
    # current_epoch + r. cumsum of resets gives r per lane (reset lane itself
    # starts the new epoch).
    reset_rank = jnp.cumsum(resets.astype(jnp.int32))
    lane_epoch = current_epoch + reset_rank

    # stable sort by (slot, lane) — lane order inside a slot is preserved.
    # slots_v is non-negative (< K+1): radix path on CPU, lax sort on TPU
    order = stable_argsort_bounded(slots_v)
    s_slots = slots_v[order]
    s_epochs = lane_epoch[order]

    # a new segment starts when slot changes OR lane epoch changes
    prev_slot = jnp.concatenate([jnp.full((1,), -1, s_slots.dtype), s_slots[:-1]])
    prev_epoch = jnp.concatenate([jnp.full((1,), -1, s_epochs.dtype), s_epochs[:-1]])
    seg_start = (s_slots != prev_slot) | (s_epochs != prev_epoch)

    safe_slots = jnp.minimum(s_slots, K - 1)

    # state writes come from the last lane of each *slot* run (unique per
    # slot, so the scatter has no duplicate indices; last epoch's value wins)
    next_slot = jnp.concatenate([s_slots[1:], jnp.full((1,), -1, s_slots.dtype)])
    is_slot_end = s_slots != next_slot
    write_slot = jnp.where((s_slots < K) & is_slot_end, s_slots, sentinel)

    L = s_slots.shape[0]
    idx = jnp.arange(L, dtype=jnp.int32)
    start_idx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(seg_start, idx, 0))

    return _SegmentPlan(order, s_slots, s_epochs, seg_start, safe_slots,
                        s_slots < K, write_slot, start_idx)


def _scan_component(values, epoch_table, deltas, valid, plan: _SegmentPlan,
                    op: str):
    """One component's segmented scan + carry + state write over a shared
    plan. Returns (new_values, sorted-order outputs)."""
    combine, identity = _OPS[op](deltas.dtype)
    s_deltas = jnp.where(valid, deltas,
                         jnp.full_like(deltas, identity))[plan.order]
    within = _segmented_scan(s_deltas, plan.seg_start, combine, identity)

    # carry-in: only the segment whose epoch matches the state's stored epoch
    # for that slot gets the stored value; stale epochs read the identity.
    stored_vals = values[plan.safe_slots]
    stored_epoch = epoch_table[plan.safe_slots]
    carry = jnp.where(
        plan.epoch_ok_slots & (stored_epoch == plan.s_epochs), stored_vals,
        jnp.full_like(stored_vals, identity))
    carry_seg = carry[plan.start_idx]  # shared start-index gather

    s_out = combine(carry_seg, within)
    new_values = values.at[plan.write_slot].set(
        s_out.astype(values.dtype), mode="drop")
    return new_values, s_out


def grouped_scan_fused(
    values_list: list,  # per component: [K] accumulator array
    shared_epoch: jax.Array,  # int32[K] — ONE epoch table for all components
    slots: jax.Array,
    deltas_list: list,  # per component: [L] signed deltas
    valid: jax.Array,
    resets: jax.Array,
    current_epoch: jax.Array,
) -> tuple[list, jax.Array, list]:
    """grouped_scan for N sum-op components sharing (slots, valid, resets):
    ONE sort, ONE segment structure, ONE epoch gather/scatter — instead of N
    of each. The dominant per-step HBM traffic for multi-aggregate queries
    (sum+avg = 3 components) drops accordingly. Semantics identical to N
    grouped_scan(op='sum') calls.

    Returns (new_values_list, new_shared_epoch, per-lane outputs list)."""
    K = shared_epoch.shape[0]
    plan = _segment_plan(slots, valid, resets, current_epoch, K)
    stored_epoch = shared_epoch[plan.safe_slots]
    epoch_live = plan.epoch_ok_slots & (stored_epoch == plan.s_epochs)
    inv_order = invert_permutation(plan.order)  # ONE scatter, n gathers
    new_values, outs = [], []
    for values, deltas in zip(values_list, deltas_list):
        sd = jnp.where(valid, deltas, jnp.zeros((), deltas.dtype))[plan.order]
        within = _segmented_scan(sd, plan.seg_start, lambda a, b: a + b,
                                 jnp.zeros((), sd.dtype))
        stored_vals = values[plan.safe_slots]
        carry = jnp.where(epoch_live, stored_vals, jnp.zeros_like(stored_vals))
        s_out = carry[plan.start_idx] + within.astype(values.dtype)
        new_values.append(values.at[plan.write_slot].set(s_out, mode="drop"))
        outs.append(s_out[inv_order])
    new_epoch = shared_epoch.at[plan.write_slot].set(
        plan.s_epochs.astype(shared_epoch.dtype), mode="drop")
    return new_values, new_epoch, outs


def ungrouped_scan(
    state: GroupState,
    deltas: jax.Array,
    valid: jax.Array,
    resets: jax.Array,
    current_epoch: jax.Array,
    op: str = "sum",
) -> tuple[GroupState, jax.Array]:
    """`grouped_scan` for the single-group case (no GROUP BY, slots all 0):
    lanes already form one slot run in arrival order, so the sort and the
    permutation scatters vanish — just a segmented scan over reset
    boundaries plus one scalar state cell. Semantics identical to
    grouped_scan with all-zero slots."""
    combine, identity = _OPS[op](deltas.dtype)
    reset_rank = jnp.cumsum(resets.astype(jnp.int32))
    lane_epoch = current_epoch + reset_rank
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), lane_epoch[1:] != lane_epoch[:-1]])
    s_deltas = jnp.where(valid, deltas, jnp.full_like(deltas, identity))
    within = _segmented_scan(s_deltas, seg_start, combine, identity)
    stored = state.values[0]
    carry_lane = jnp.where(state.epoch[0] == lane_epoch, stored,
                           jnp.full_like(stored, identity))
    carry_at_start = jnp.where(seg_start, carry_lane,
                               jnp.full_like(carry_lane, identity))
    carry_seg = _segment_broadcast_op(carry_at_start, seg_start, identity)
    s_out = combine(carry_seg, within)
    new_state = GroupState(
        values=state.values.at[0].set(s_out[-1].astype(state.values.dtype)),
        epoch=state.epoch.at[0].set(lane_epoch[-1].astype(state.epoch.dtype)))
    return new_state, s_out


def ungrouped_scan_fused(
    values_list: list,
    shared_epoch: jax.Array,
    deltas_list: list,
    valid: jax.Array,
    resets: jax.Array,
    current_epoch: jax.Array,
) -> tuple[list, jax.Array, list]:
    """`grouped_scan_fused` without GROUP BY: shared reset segmentation, no
    sort, scalar state cells."""
    reset_rank = jnp.cumsum(resets.astype(jnp.int32))
    lane_epoch = current_epoch + reset_rank
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), lane_epoch[1:] != lane_epoch[:-1]])
    epoch_ok = shared_epoch[0] == lane_epoch
    new_values, outs = [], []
    for values, deltas in zip(values_list, deltas_list):
        combine, identity = _OPS["sum"](deltas.dtype)
        s_deltas = jnp.where(valid, deltas, jnp.full_like(deltas, identity))
        within = _segmented_scan(s_deltas, seg_start, combine, identity)
        carry_lane = jnp.where(epoch_ok, values[0],
                               jnp.full_like(values[0], identity))
        carry_at_start = jnp.where(seg_start, carry_lane,
                                   jnp.full_like(carry_lane, identity))
        carry_seg = _segment_broadcast_op(carry_at_start, seg_start, identity)
        s_out = combine(carry_seg, within)
        new_values.append(values.at[0].set(s_out[-1].astype(values.dtype)))
        outs.append(s_out)
    new_epoch = shared_epoch.at[0].set(lane_epoch[-1].astype(
        shared_epoch.dtype))
    return new_values, new_epoch, outs


def _op_sum(dtype):
    if dtype == jnp.bool_:
        return jnp.logical_or, False
    return jnp.add, jnp.zeros((), dtype)


def _op_min(dtype):
    ident = jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer) else jnp.inf
    return jnp.minimum, jnp.asarray(ident, dtype)


def _op_max(dtype):
    ident = jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer) else -jnp.inf
    return jnp.maximum, jnp.asarray(ident, dtype)


_OPS = {"sum": _op_sum, "min": _op_min, "max": _op_max}


def _segmented_scan(vals: jax.Array, seg_start: jax.Array, combine, identity) -> jax.Array:
    """Inclusive scan that restarts at each segment start (classic conditional
    associative scan: carry a (reset_flag, value) pair)."""

    def op(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, combine(av, bv))

    flags = seg_start
    _, out = jax.lax.associative_scan(op, (flags, vals))
    return out


def _segment_broadcast_op(vals_at_start: jax.Array, seg_start: jax.Array, identity) -> jax.Array:
    """Broadcast each segment-start value across its segment."""
    L = seg_start.shape[0]
    idx = jnp.arange(L)
    start_idx = jnp.where(seg_start, idx, 0)
    start_idx = jax.lax.associative_scan(jnp.maximum, start_idx)
    return vals_at_start[start_idx]


# --- device-side key tables -----------------------------------------------------


class KeyTable(NamedTuple):
    """Append-only device dictionary: 64-bit composite keys → dense int32 ids.

    Replaces the reference's string-concat HashMap group-by keys
    (GroupByKeyGenerator.java:37) for non-string keys, fully on device as an
    **open-addressing hash table**: lookup and insert are a handful of
    int32-addressed gathers plus one conflict-resolving scatter — no sort.
    (The previous sorted-merge design argsorted the whole [K] int64 table
    every step: ~4.8 s/step at K=1M on v5e, where s64 sorting is
    software-emulated.) The hash array has 2x the id capacity, keeping load
    ≤ 50% even at a full id space, so double-hashed probe windows practically
    never exhaust. Ids are dense in [0, count) but assigned in hash-slot
    order per batch, NOT first-appearance order — use DenseKeyTable where
    first-appearance ordering matters.
    """

    keys: jax.Array  # int64[H = 2K]; _KEY_PAD marks an empty slot
    ids: jax.Array  # int32[H] dense id of the key stored at each slot
    count: jax.Array  # int32 number of live keys (ids assigned)
    misses: jax.Array  # int32 lifetime lanes left unresolved (aliased to id 0)


_KEY_PAD = jnp.iinfo(jnp.int64).max

#: probe window per lookup; at ≤50% hash load, P(window exhausted) ≈ α^D —
#: negligible. The 85%-of-K capacity monitors fire long before misses matter.
_PROBE_DEPTH = 16
#: insert retry rounds (each round: probe, claim-by-min scatter, verify).
#: A mass insert of k new keys into H slots loses ~k²/2H first-wave races,
#: shrinking geometrically per wave — 5 claim waves cover a full-batch
#: insert into a small table with negligible residual.
_INSERT_ROUNDS = 6


def init_key_table(capacity: int) -> KeyTable:
    H = 2 * capacity
    return KeyTable(
        keys=jnp.full((H,), _KEY_PAD, dtype=jnp.int64),
        ids=jnp.zeros((H,), dtype=jnp.int32),
        count=jnp.int32(0),
        misses=jnp.int32(0),
    )


def key_lookup_or_insert(
    table: KeyTable, keys: jax.Array, valid: jax.Array
) -> tuple[KeyTable, jax.Array, jax.Array]:
    """Resolve each lane's key to a dense id, inserting unseen keys.

    Returns (new_table, ids[L], resolved[L]). Invalid lanes get id 0 and
    resolved=False. Lanes whose key could not be placed (id space or probe
    window exhausted) also come back unresolved — callers must DROP them
    from downstream scans (monitored truncation via table.misses) rather
    than let them alias id 0.

    Parallel-insert race (two lanes claiming one empty slot) resolves
    deterministically: both scatter with `.min(key)`, the smaller key wins
    (PAD is int64 max, so any key beats an empty slot), losers re-probe with
    their next window slot the following round. Same-key duplicate lanes
    claim the same slot with the same value and all win together.
    """
    L = keys.shape[0]
    H = table.keys.shape[0]
    K = H // 2  # id capacity
    keys = keys.astype(jnp.int64)
    # avoid colliding with the pad sentinel
    keys = jnp.where(keys == _KEY_PAD, _KEY_PAD - 1, keys)

    # probe base + odd stride from the two int32 halves of the key (double
    # hashing kills linear clustering; no emulated s64 math anywhere)
    halves = jax.lax.bitcast_convert_type(keys, jnp.int32)  # [L, 2]
    h32 = (halves[..., 0] ^ halves[..., 1]).astype(jnp.uint32)
    h32 = h32 * jnp.uint32(0x9E3779B9)  # golden-ratio scramble
    base = (h32 % jnp.uint32(H)).astype(jnp.int32)
    stride = (1 + 2 * ((h32 >> 16) & jnp.uint32(7))).astype(jnp.int32)
    probe_off = jnp.arange(_PROBE_DEPTH, dtype=jnp.int32)
    pslots = (base[:, None] + probe_off * stride[:, None]) % H

    def probe(tbl, need, slot_of, wslot, won):
        """One probe round over an int32 view (TPU random gathers are slow;
        a [L,D,2] int32 gather is ~2.5x cheaper than the s64 gather)."""
        pk32 = jax.lax.bitcast_convert_type(tbl, jnp.int32)[pslots]  # [L,D,2]
        match = ((pk32[..., 0] == halves[:, None, 0])
                 & (pk32[..., 1] == halves[:, None, 1]))
        has_match = jnp.any(match, axis=-1)
        midx = jnp.argmax(match, axis=-1)
        mslot = jnp.take_along_axis(pslots, midx[:, None], axis=-1)[:, 0]
        hit = need & has_match
        slot_of = jnp.where(hit, mslot, slot_of)
        # a lane that finds its key at the slot it claimed last round won a
        # new entry (same-key duplicates all win together; deduped later)
        won = won | (hit & (mslot == wslot))
        need = need & ~has_match
        # first empty slot in each window, for the next claim wave
        pad32 = jax.lax.bitcast_convert_type(jnp.int64(_KEY_PAD), jnp.int32)
        empty = (pk32[..., 0] == pad32[0]) & (pk32[..., 1] == pad32[1])
        has_empty = jnp.any(empty, axis=-1)
        eidx = jnp.argmax(empty, axis=-1)
        eslot = jnp.take_along_axis(pslots, eidx[:, None], axis=-1)[:, 0]
        return need, slot_of, won, has_empty, eslot

    slot_of = jnp.zeros((L,), jnp.int32)  # resolved hash slot per lane
    won = jnp.zeros((L,), bool)  # lanes whose claim created a new entry
    wslot = jnp.full((L,), -1, jnp.int32)
    need, slot_of, won, has_empty, eslot = probe(
        table.keys, valid, slot_of, wslot, won)

    def do_insert(args):
        tbl, id_arr, count, need, slot_of, won, has_empty, eslot = args
        wslot = jnp.full((L,), -1, jnp.int32)
        for r in range(_INSERT_ROUNDS - 1):
            claim = need & has_empty
            cand = jnp.where(claim, eslot, H)
            tbl = tbl.at[cand].min(keys, mode="drop")
            wslot = jnp.where(claim, eslot, -1)
            need, slot_of, won, has_empty, eslot = probe(
                tbl, need, slot_of, wslot, won)
        # assign dense ids to the batch's new entries: unique winning slots,
        # ranked in slot order (int32 sort over L lanes — cheap and native)
        ws = jnp.where(won, slot_of, H)
        sw = jnp.sort(ws)
        uniq = (jnp.concatenate([jnp.ones((1,), bool), sw[1:] != sw[:-1]])
                & (sw < H))
        rank = (jnp.cumsum(uniq.astype(jnp.int32)) - 1).astype(jnp.int32)
        new_id = (count + rank).astype(jnp.int32)
        # entries past the id capacity are REVERTED to empty slots (leaving
        # them stored with an aliased id would corrupt group 0 and make dead
        # pairs look live to the compactor); their lanes count as misses via
        # the final verification gather below
        over = uniq & (new_id >= K)
        tbl = tbl.at[jnp.where(over, sw, H)].set(_KEY_PAD, mode="drop")
        keep = uniq & (new_id < K)
        id_arr = id_arr.at[jnp.where(keep, sw, H)].set(new_id, mode="drop")
        n_new = jnp.sum(keep, dtype=jnp.int32)
        return tbl, id_arr, jnp.minimum(count + n_new, jnp.int32(K)), need, \
            slot_of

    def no_insert(args):
        tbl, id_arr, count, need, slot_of = args[:5]
        return tbl, id_arr, count, need, slot_of

    # steady state (every key already present) skips the claim/verify waves
    # entirely — inserts are batch-rare, lookups are every-step
    tbl, id_arr, count, need, slot_of = jax.lax.cond(
        jnp.any(need), do_insert, no_insert,
        (table.keys, table.ids, table.count, need, slot_of, won, has_empty,
         eslot))

    # final verification: a lane is resolved only if its slot still stores
    # its key (overflow reverts and races can undo an apparent win)
    t32 = jax.lax.bitcast_convert_type(tbl, jnp.int32)[slot_of]
    final_ok = (t32[:, 0] == halves[:, 0]) & (t32[:, 1] == halves[:, 1])
    resolved = valid & ~need & final_ok
    ids = jnp.where(resolved, id_arr[slot_of], 0)
    # unresolved lanes alias id 0; the lifetime counter lets runtime monitors
    # surface it (id-space exhaustion or probe-window exhaustion — rare but
    # nonzero even below the 85% capacity thresholds)
    misses = table.misses + jnp.sum(valid & ~resolved, dtype=jnp.int32)
    return (KeyTable(keys=tbl, ids=id_arr, count=count, misses=misses),
            ids, resolved)


class DenseKeyTable(NamedTuple):
    """Sorted-merge key table assigning DENSE ids in first-appearance order
    (the original design). Only for small capacities — inserts argsort the
    whole [K] table, which is emulated-s64-expensive at scale — where
    downstream state is packed per-id (e.g. the sharded-partition slot axis,
    which vmaps over [0, n_slots))."""

    sorted_keys: jax.Array  # int64[K], padded with INT64_MAX
    sorted_ids: jax.Array  # int32[K]
    count: jax.Array  # int32 number of live keys


def init_dense_key_table(capacity: int) -> DenseKeyTable:
    return DenseKeyTable(
        sorted_keys=jnp.full((capacity,), _KEY_PAD, dtype=jnp.int64),
        sorted_ids=jnp.zeros((capacity,), dtype=jnp.int32),
        count=jnp.int32(0),
    )


def dense_key_lookup_or_insert(
    table: DenseKeyTable, keys: jax.Array, valid: jax.Array
) -> tuple[DenseKeyTable, jax.Array]:
    """Resolve each lane's key to a dense id, inserting unseen keys.

    Returns (new_table, ids[L]). Invalid lanes get id 0 (callers mask them).
    Overflow beyond capacity silently reuses id 0 — callers size K generously
    and monitor table.count.
    """
    L = keys.shape[0]
    K = table.sorted_keys.shape[0]
    keys = keys.astype(jnp.int64)
    # avoid colliding with the pad sentinel
    keys = jnp.where(keys == _KEY_PAD, _KEY_PAD - 1, keys)

    pos = searchsorted32(table.sorted_keys, keys)
    pos_c = jnp.clip(pos, 0, K - 1)
    found = table.sorted_keys[pos_c] == keys
    existing_ids = table.sorted_ids[pos_c]

    # identify first occurrence of each new key within the batch, in lane order
    is_new = valid & ~found
    nk = jnp.where(is_new, keys, _KEY_PAD)
    order = jnp.argsort(nk, stable=True)  # groups duplicates, keeps lane order
    snk = nk[order]
    first = jnp.concatenate([jnp.ones((1,), bool), snk[1:] != snk[:-1]]) & (snk != _KEY_PAD)
    # rank new unique keys by first-appearance lane index for deterministic ids
    first_lane = jnp.where(first, order, L)
    lane_rank = invert_permutation(jnp.argsort(first_lane, stable=True))
    new_id_sorted = table.count + lane_rank.astype(jnp.int32)

    # each lane's id: for new keys, find their unique-key id via the sorted run
    run_id = _segment_broadcast_op(
        jnp.where(first, new_id_sorted, 0), first | (snk == _KEY_PAD), 0)
    lane_new_ids = jnp.zeros((L,), jnp.int32).at[order].set(
        jnp.where(snk != _KEY_PAD, run_id, 0).astype(jnp.int32))

    ids = jnp.where(found, existing_ids, lane_new_ids)
    ids = jnp.where(valid, ids, 0)

    # merge new unique keys into the sorted table
    n_new = jnp.sum(first.astype(jnp.int32))
    merged_keys = jnp.concatenate([table.sorted_keys,
                                   jnp.where(first, snk, _KEY_PAD)])
    merged_ids = jnp.concatenate([table.sorted_ids,
                                  jnp.where(first, new_id_sorted, 0)])
    morder = jnp.argsort(merged_keys, stable=True)[:K]
    new_table = DenseKeyTable(
        sorted_keys=merged_keys[morder],
        sorted_ids=merged_ids[morder],
        count=jnp.minimum(table.count + n_new, K),
    )
    return new_table, ids


def hash_columns32(cols: list[jax.Array]) -> jax.Array:
    """32-bit column mix for candidate generation (join probes): all math in
    u32 — the 64-bit variant's u64 multiplies are software-emulated on TPU
    and show up at 100k-row build windows. Collisions only cost re-verified
    candidates, never correctness (callers re-check the exact condition)."""
    h = jnp.uint32(0x811C9DC5)
    for c in cols:
        if jnp.issubdtype(c.dtype, jnp.floating):
            c = jax.lax.bitcast_convert_type(
                c, jnp.int32 if c.dtype.itemsize == 4 else jnp.int64)
        if c.dtype.itemsize == 8:
            w = jax.lax.bitcast_convert_type(c, jnp.int32)
            words = [w[..., 0], w[..., 1]]
        else:  # 4-byte ints and bool
            words = [c.astype(jnp.int32)]
        for x in words:
            h = (h ^ x.astype(jnp.uint32)) * jnp.uint32(0x01000193)
            h = h ^ (h >> 15)
    return h


def hash_columns(cols: list[jax.Array]) -> jax.Array:
    """Combine multiple key columns into one int64 key (fxhash-style mix).
    Collision probability over 64 bits is negligible for CEP key cardinalities.
    Float columns hash by BIT PATTERN (like Java's Double.hashCode), not by
    int truncation — 1.2 and 1.9 are distinct keys."""
    h = jnp.uint64(0xCBF29CE484222325)
    for c in cols:
        if jnp.issubdtype(c.dtype, jnp.floating):
            bits = jax.lax.bitcast_convert_type(
                c, jnp.int32 if c.dtype == jnp.float32 else jnp.int64)
            x = bits.astype(jnp.int64).astype(jnp.uint64)
        else:
            x = c.astype(jnp.int64).astype(jnp.uint64)
        h = (h ^ x) * jnp.uint64(0x100000001B3)
        h = h ^ (h >> 29)
    return h.astype(jnp.int64)


# --- host-side key dictionaries -------------------------------------------------


class KeyDictionary:
    """Host-side composite-key → dense slot assignment for group-by keys that are
    not already dense codes. Append-only; snapshot/restorable. The TPU analogue
    of the reference's group-by key strings: here a key becomes one int32 the
    device can scatter with."""

    def __init__(self) -> None:
        self._map: dict[tuple, int] = {}

    def assign(self, keys) -> "list[int]":
        out = []
        m = self._map
        for k in keys:
            slot = m.get(k)
            if slot is None:
                slot = len(m)
                m[k] = slot
            out.append(slot)
        return out

    def __len__(self) -> int:
        return len(self._map)

    def snapshot(self) -> list:
        return sorted(self._map.items(), key=lambda kv: kv[1])

    def restore(self, items) -> None:
        self._map = {tuple(k) if isinstance(k, list) else k: v for k, v in items}
