"""Attribute aggregators, decomposed into grouped-scan components.

Reference: the 13 aggregator executors under
core/query/selector/attribute/aggregator/ (SumAttributeAggregatorExecutor.java:69
et al.) each keep a per-group-key mutable state with processAdd/processRemove.
TPU re-design: an aggregator is a set of *components*, each a per-key
accumulator driven by ops/groupby.grouped_scan with signed per-lane deltas
(CURRENT lanes add, EXPIRED lanes subtract, RESET lanes epoch-bump), plus a
`finalize` combining component values per lane. avg = sum/count, stdDev =
(sumsq, sum, count), and/or = counts of false/true — all become fused scans.

min/max are monotone scans (op="min"/"max"); they cannot process removals, so
the planner rejects them over sliding windows (matching limitation called out
in SURVEY §7; a segment-tree ring is the planned upgrade).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from ..query_api.definition import AttributeType

_T = AttributeType


@dataclass(frozen=True)
class Component:
    """One per-key accumulator: delta(args_value_array, sign) -> [L] deltas."""

    dtype: object
    delta: Callable  # (vals: [L] or None, sign: [L] ±1 float) -> [L] deltas
    op: str = "sum"
    #: monotone components ignore EXPIRED lanes (sign<0) instead of erroring
    ignore_removal: bool = False
    #: survives RESET (minForever/maxForever)
    ignore_reset: bool = False


@dataclass(frozen=True)
class AggregatorSpec:
    components: tuple[Component, ...]
    finalize: Callable  # (list of per-lane component arrays) -> [L] values
    return_type: AttributeType
    #: needs removal support (sliding windows); min/max set False
    supports_removal: bool = True
    #: stateful aggregators that don't decompose into scan components
    #: (distinctCount): init_custom(group_capacity, grouped) -> state pytree;
    #: custom_scan(state, slots, arg_vals, sign, lane_valid, resets, epoch,
    #: grouped) -> (state', per-lane values). `grouped` is a static planner
    #: hint: ungrouped queries pass all-zero slots, which admits cheaper
    #: state layouts.
    init_custom: Optional[Callable] = None
    custom_scan: Optional[Callable] = None
    #: 'min'/'max' — marks true-extrema aggregators so sliding-window
    #: planners can swap in the removal-capable range-query path (the
    #: monotone component scan cannot undo removals)
    extrema_op: Optional[str] = None


class AggregatorFactory:
    """SPI: make(arg_types) -> AggregatorSpec."""

    def __init__(self, make: Callable):
        self.make = make


def _sum_return_type(t: AttributeType) -> AttributeType:
    # reference SumAttributeAggregatorExecutor: int/long -> LONG, float/double -> DOUBLE
    return _T.LONG if t in (_T.INT, _T.LONG) else _T.DOUBLE


def _make_sum(arg_types):
    t = arg_types[0]
    if not dtypes.is_numeric(t):
        raise SiddhiAppCreationError(f"sum() over non-numeric {t}")
    rt = _sum_return_type(t)
    dt = dtypes.device_dtype(rt)
    comp = Component(dtype=dt, delta=lambda v, sign: v.astype(dt) * sign.astype(dt))
    return AggregatorSpec((comp,), lambda cs: cs[0], rt)


def _make_count(arg_types):
    dt = dtypes.device_dtype(_T.LONG)
    comp = Component(dtype=dt, delta=lambda v, sign: sign.astype(dt))
    return AggregatorSpec((comp,), lambda cs: cs[0], _T.LONG)


def _make_avg(arg_types):
    t = arg_types[0]
    if not dtypes.is_numeric(t):
        raise SiddhiAppCreationError(f"avg() over non-numeric {t}")
    dt = dtypes.device_dtype(_T.DOUBLE)
    s = Component(dtype=dt, delta=lambda v, sign: v.astype(dt) * sign.astype(dt))
    c = Component(dtype=dt, delta=lambda v, sign: sign.astype(dt))

    def fin(cs):
        total, n = cs
        return jnp.where(n != 0, total / jnp.where(n != 0, n, 1), jnp.zeros_like(total))

    return AggregatorSpec((s, c), fin, _T.DOUBLE)


def _make_minmax(op: str):
    def make(arg_types):
        t = arg_types[0]
        if not dtypes.is_numeric(t):
            raise SiddhiAppCreationError(f"{op}() over non-numeric {t}")
        dt = dtypes.device_dtype(t)
        comp = Component(dtype=dt, delta=lambda v, sign: v.astype(dt), op=op,
                         ignore_removal=True)
        return AggregatorSpec((comp,), lambda cs: cs[0], t,
                              supports_removal=False, extrema_op=op)

    return make


def _make_minmax_forever(op: str):
    def make(arg_types):
        t = arg_types[0]
        dt = dtypes.device_dtype(t)
        comp = Component(dtype=dt, delta=lambda v, sign: v.astype(dt), op=op,
                         ignore_removal=True, ignore_reset=True)
        return AggregatorSpec((comp,), lambda cs: cs[0], t, supports_removal=True)

    return make


def _make_stddev(arg_types):
    t = arg_types[0]
    if not dtypes.is_numeric(t):
        raise SiddhiAppCreationError(f"stdDev() over non-numeric {t}")
    dt = dtypes.device_dtype(_T.DOUBLE)
    s = Component(dtype=dt, delta=lambda v, sign: v.astype(dt) * sign.astype(dt))
    s2 = Component(dtype=dt, delta=lambda v, sign: (v.astype(dt) ** 2) * sign.astype(dt))
    c = Component(dtype=dt, delta=lambda v, sign: sign.astype(dt))

    def fin(cs):
        total, sumsq, n = cs
        safe_n = jnp.where(n != 0, n, 1)
        mean = total / safe_n
        var = sumsq / safe_n - mean * mean
        # population std dev (reference StdDevAttributeAggregatorExecutor)
        return jnp.where(n != 0, jnp.sqrt(jnp.maximum(var, 0.0)), jnp.zeros_like(var))

    return AggregatorSpec((s, s2, c), fin, _T.DOUBLE)


def _make_bool_and(arg_types):
    # and(bool): true while no false values in window — count falses
    dt = dtypes.device_dtype(_T.LONG)
    c = Component(dtype=dt, delta=lambda v, sign: jnp.where(~v, sign.astype(dt), 0))

    def fin(cs):
        return cs[0] == 0

    return AggregatorSpec((c,), fin, _T.BOOL)


def _make_bool_or(arg_types):
    dt = dtypes.device_dtype(_T.LONG)
    c = Component(dtype=dt, delta=lambda v, sign: jnp.where(v, sign.astype(dt), 0))

    def fin(cs):
        return cs[0] > 0

    return AggregatorSpec((c,), fin, _T.BOOL)


def _make_distinct_count(arg_types):
    """distinctCount(attr) — EXACT distinct values per group with full
    add/remove support (reference: DistinctCountAttributeAggregatorExecutor
    keeps a value→count HashMap per group key).

    TPU design: one device hash table over (group, value) PAIRS shared by all
    groups + a per-group distinct counter. Two chained grouped scans per
    batch: (1) per-pair signed counts — a CURRENT lane whose post-update pair
    count == 1 is a 0→1 transition (+1 distinct), an EXPIRED lane reaching 0
    is a 1→0 transition (-1); (2) those ±1 deltas scanned per group give the
    per-lane running distinct count, preserving the reference's event-at-a-time
    emission semantics inside a batch.

    Fast path: an UNGROUPED distinctCount over a STRING attribute needs no
    hash table at all — device strings are dictionary codes, already dense
    ids into the interning table, so the code indexes the pair-count table
    directly (codes ≥ capacity are dropped with the same documented overflow
    semantics; the runtime monitors interning size against capacity)."""
    from .groupby import (
        grouped_scan,
        hash_columns,
        init_group_state,
        init_key_table,
        key_lookup_or_insert,
        ungrouped_scan,
    )

    dt = dtypes.device_dtype(_T.LONG)
    code_arg = bool(arg_types) and arg_types[0] == _T.STRING

    def init_custom(group_capacity: int, grouped: bool = True):
        P = group_capacity  # (group, value) pair capacity
        if code_arg and not grouped:
            return (init_group_state(P, dt), init_group_state(1, dt))
        return (init_key_table(P), init_group_state(P, dt),
                init_group_state(group_capacity, dt))

    def custom_scan(state, slots, arg_vals, sign, lane_valid, resets, epoch,
                    grouped: bool = True):
        deltas = sign.astype(dt)
        if code_arg and not grouped:
            pair_counts, distinct = state
            P = pair_counts.values.shape[0]
            code = arg_vals[0].astype(jnp.int32)
            ok = lane_valid & (code >= 0) & (code < P)
            pair_counts2, pair_post = grouped_scan(
                pair_counts, code, deltas, ok, resets, epoch, op="sum")
            dd = jnp.where(sign > 0,
                           (pair_post == 1).astype(dt),
                           -(pair_post == 0).astype(dt))
            distinct2, out = ungrouped_scan(
                distinct, dd, ok, resets, epoch, op="sum")
            return (pair_counts2, distinct2), out
        kt, pair_counts, distinct = state
        pk = hash_columns([slots.astype(jnp.int64), arg_vals[0]])
        kt2, pair_slots, kres = key_lookup_or_insert(kt, pk, lane_valid)
        # drop unresolved lanes entirely (pair table exhausted — monitored
        # truncation) instead of corrupting pair slot 0
        lane_valid = lane_valid & kres
        pair_counts2, pair_post = grouped_scan(
            pair_counts, pair_slots, deltas, lane_valid, resets, epoch,
            op="sum")
        dd = jnp.where(sign > 0,
                       (pair_post == 1).astype(dt),
                       -(pair_post == 0).astype(dt))
        if grouped:
            distinct2, out = grouped_scan(
                distinct, slots, dd, lane_valid, resets, epoch, op="sum")
        else:
            distinct2, out = ungrouped_scan(
                distinct, dd, lane_valid, resets, epoch, op="sum")
        return (kt2, pair_counts2, distinct2), out

    return AggregatorSpec((), lambda cs: cs[0], _T.LONG,
                          init_custom=init_custom, custom_scan=custom_scan)


class HLLState(NamedTuple):
    """hll:distinctCount sketch state; `dropped` counts lanes whose group
    slot exceeded config.hll_group_capacity (monitored overflow)."""

    regs: jax.Array  # int32[G * M] registers
    dropped: jax.Array  # int64 lifetime lanes with no sketch


def _make_hll_distinct_count(arg_types):
    """hll:distinctCount(attr) — APPROXIMATE distinct count via a
    HyperLogLog sketch (BASELINE.md config 3 names the HLL variant; the
    EXACT pair-table distinctCount stays the default `distinctCount`).

    m = config.hll_registers registers per group (standard error
    ~1.04/sqrt(m): 1024 → ~3.3%). Each CURRENT lane scatter-maxes one
    register with the rank of its value-hash; the per-group estimate is the
    classic alpha_m * m^2 / sum(2^-M) harmonic mean with the small-range
    linear-counting correction. Removals (sliding EXPIRED lanes) are
    IGNORED — a sketch cannot forget; use exact distinctCount where
    sliding-window removal matters. RESET (batch-window flush) clears the
    registers. Per-lane emission reports the POST-BATCH estimate
    (documented batch-granularity divergence from per-event emission)."""
    from .groupby import hash_columns

    dt = dtypes.device_dtype(_T.LONG)
    M = int(dtypes.config.hll_registers)
    P_BITS = M.bit_length() - 1
    assert M == 1 << P_BITS, "hll_registers must be a power of two"

    def init_custom(group_capacity: int, grouped: bool = True):
        G = (min(group_capacity, dtypes.config.hll_group_capacity)
             if grouped else 1)
        return HLLState(regs=jnp.zeros((G * M,), jnp.int32),
                        dropped=jnp.int64(0))

    def _estimate(regs):
        R = regs.reshape(-1, M).astype(jnp.float32)
        inv = jnp.sum(jnp.exp2(-R), axis=1)
        alpha = 0.7213 / (1.0 + 1.079 / M)
        E = alpha * M * M / inv
        zeros = jnp.sum(R == 0, axis=1)
        lin = M * jnp.log(M / jnp.maximum(zeros, 1).astype(jnp.float32))
        E = jnp.where((E <= 2.5 * M) & (zeros > 0), lin, E)
        return jnp.round(E).astype(dt)

    def custom_scan(state, slots, arg_vals, sign, lane_valid, resets, epoch,
                    grouped: bool = True):
        regs = state.regs
        G = regs.shape[0] // M
        h = hash_columns([arg_vals[0]]).astype(jnp.uint64)
        # murmur3 fmix64 avalanche: the column mix leaves low bits
        # correlated for dense inputs (string codes!), which skews both the
        # register index and the rank distribution
        h = h ^ (h >> 33)
        h = h * jnp.uint64(0xFF51AFD7ED558CCD)
        h = h ^ (h >> 33)
        h = h * jnp.uint64(0xC4CEB9FE1A85EC53)
        h = h ^ (h >> 33)
        j = (h & jnp.uint64(M - 1)).astype(jnp.int32)
        w = (h >> jnp.uint64(P_BITS)).astype(jnp.uint32)
        rho = jax.lax.clz(
            jax.lax.bitcast_convert_type(w, jnp.int32)) + 1
        in_cap = (slots >= 0) & (slots < G)
        ok = lane_valid & (sign > 0) & in_cap
        idx = jnp.where(ok, slots * M + j, G * M)
        sl = jnp.clip(slots, 0, G - 1)
        # group slots beyond hll_group_capacity track NO sketch: emit 0 and
        # count them (collect_overflow surfaces the counter with a warning)
        n_drop = jnp.sum(lane_valid & (sign > 0) & ~in_cap, dtype=jnp.int64)

        # RESET handling at lane position (batch-window flushes mid-chunk):
        # lanes BEFORE the first reset continue the incoming sketch; lanes
        # AFTER the last reset start a fresh one. Chunks holding >1 reset
        # approximate the middle segments with the final sketch's estimate
        # (documented — sketches are for large windows; a tiny batch window
        # flushing several times per chunk wants exact distinctCount).
        n_resets = jnp.sum(resets, dtype=jnp.int32)
        rk = jnp.cumsum(resets.astype(jnp.int32))
        before_first = rk == 0
        after_last = rk == n_resets

        regs_a = regs.at[jnp.where(before_first, idx, G * M)].max(
            rho, mode="drop")
        est_a = _estimate(regs_a)[sl]
        fresh = jnp.where(n_resets > 0, jnp.zeros_like(regs), regs)
        regs_b = fresh.at[jnp.where(after_last, idx, G * M)].max(
            rho, mode="drop")
        est_b = _estimate(regs_b)[sl]
        out = jnp.where(before_first & (n_resets > 0), est_a, est_b)
        out = jnp.where(in_cap, out, jnp.zeros_like(out))
        return HLLState(regs=regs_b, dropped=state.dropped + n_drop), out

    return AggregatorSpec((), lambda cs: cs[0], _T.LONG,
                          init_custom=init_custom, custom_scan=custom_scan)


_COMPACTION_INSERT = None


def _compaction_insert():
    """Module-cached jitted insert — a fresh jax.jit wrapper per compaction
    would retrace/recompile every time."""
    global _COMPACTION_INSERT
    if _COMPACTION_INSERT is None:
        from .groupby import key_lookup_or_insert
        _COMPACTION_INSERT = jax.jit(key_lookup_or_insert)
    return _COMPACTION_INSERT


def compact_distinct_state(state, current_epoch: int):
    """Evict dead pairs from a distinctCount hash-path state tuple.

    The pair table is append-only inside the jitted step (zeroed pairs keep
    their slot, unlike the reference's HashMap entry removal) — lifetime-
    unique (group,value) pairs eventually fill it. This host-triggered
    rebuild re-inserts only LIVE pairs (count != 0 at the current epoch)
    into a fresh table, reclaiming every dead slot. Mirrors the reference's
    natural HashMap removal and AggregationRuntime-style eviction rebuilds.

    Called by the runtime's capacity monitor, never from inside a step.
    """
    from .groupby import GroupState, init_key_table, key_lookup_or_insert

    kt, pair_counts, distinct = state
    H = kt.keys.shape[0]
    K = H // 2
    keys = np.asarray(kt.keys)
    ids = np.asarray(kt.ids)
    vals = np.asarray(pair_counts.values)
    eps = np.asarray(pair_counts.epoch)
    occupied = keys != np.iinfo(np.int64).max
    live = occupied & (vals[ids] != 0) & (eps[ids] == current_epoch)
    live_keys = keys[live]
    live_vals = vals[ids[live]]

    fresh = init_key_table(K)
    new_vals = np.zeros((K,), vals.dtype)
    insert = _compaction_insert()
    CH = 65536
    n = live_keys.shape[0]
    for i in range(0, max(n, 1), CH):
        chunk = live_keys[i:i + CH]
        if chunk.shape[0] == 0:
            break
        pad = CH - chunk.shape[0]
        ck = jnp.asarray(np.pad(chunk, (0, pad)))
        cv = jnp.ones((CH,), bool).at[CH - pad:].set(False) if pad else \
            jnp.ones((CH,), bool)
        fresh, new_ids, ok = insert(fresh, ck, cv)
        new_ids = np.asarray(new_ids)[:chunk.shape[0]]
        ok = np.asarray(ok)[:chunk.shape[0]]
        new_vals[new_ids[ok]] = live_vals[i:i + CH][ok]

    dt = pair_counts.values.dtype
    rebuilt = GroupState(
        values=jnp.asarray(new_vals, dt),
        epoch=jnp.full((K,), current_epoch,
                       pair_counts.epoch.dtype))
    return (fresh, rebuilt, distinct)


def _make_union_set(arg_types):
    """unionSet(set) — reference UnionSetAttributeAggregatorExecutor
    aggregates java.util.Sets. Host-opaque objects cannot ride device
    streams; the supported composition sizeOfSet(unionSet(createSet(x)))
    is rewritten to an exact distinctCount at plan time (ops/selector.py
    _rewrite_set_idioms) before this factory would ever run."""
    raise SiddhiAppCreationError(
        "unionSet() inside a larger expression is not supported on this "
        "engine (raw `select unionSet(x) as s` IS — the set materializes "
        "host-side at the callback boundary); "
        "use sizeOfSet(unionSet(...)), which compiles to an exact distinct "
        "count on device")


def register_all() -> None:
    reg = lambda name, make: GLOBAL.register(  # noqa: E731
        ExtensionKind.AGGREGATOR, "", name, AggregatorFactory(make))
    reg("sum", _make_sum)
    reg("count", _make_count)
    reg("avg", _make_avg)
    reg("min", _make_minmax("min"))
    reg("max", _make_minmax("max"))
    reg("minForever", _make_minmax_forever("min"))
    reg("maxForever", _make_minmax_forever("max"))
    reg("stdDev", _make_stddev)
    reg("and", _make_bool_and)
    reg("or", _make_bool_or)
    reg("distinctCount", _make_distinct_count)
    reg("unionSet", _make_union_set)
    GLOBAL.register(ExtensionKind.AGGREGATOR, "hll", "distinctCount",
                    AggregatorFactory(_make_hll_distinct_count))


register_all()
