"""Window operators as pure `(state, batch) -> (state, chunk)` device functions.

Reference counterpart: the 30 WindowProcessor classes under
core/query/processor/stream/window/ that walk per-event linked lists and keep
`SnapshotableStreamEventQueue` heaps. TPU re-design:

- window contents live in **fixed-capacity device ring buffers** (one array per
  column + timestamps), addressed by monotonically growing 64-bit "overall
  arrival indices" (slot = idx % capacity);
- a step consumes a columnar micro-batch and emits a **chunk**: a wider
  EventBatch whose lanes are typed CURRENT / EXPIRED / RESET and ordered
  exactly as the reference's per-event chunk would interleave them
  (e.g. LengthWindowProcessor.java:118-122 emits [expired, current] per
  arrival; LengthBatchWindowProcessor.java:210-243 emits
  [expired(prev flush), RESET, current(flush)] at each flush boundary);
- ordering is produced by a single stable sort on an emission key, so the
  whole window step is one fused XLA program with static shapes.

The downstream selector consumes chunks with signed-delta grouped scans
(ops/groupby.py), reproducing per-event aggregate semantics.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from .search import searchsorted32, stable_partition_order
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError

# emission-key kinds: expired lanes sort before reset before current at the
# same trigger position (matches reference chunk insertion order).
KIND_EXPIRED = 0
KIND_RESET = 1
KIND_CURRENT = 2

# Python int, NOT a jnp scalar: a device-resident constant captured by a jit
# closure forces a per-execution constant upload on the axon TPU tunnel
# (~4.6 ms/step measured) — literals trace into the HLO for free.
BIG = 2**62


# --------------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------------- #


def compact(batch: EventBatch) -> tuple[dict, jax.Array, jax.Array, jax.Array]:
    """Stable-partition valid CURRENT lanes to the front.

    Returns (cols, ts, n_valid, order). Lanes >= n_valid hold garbage.
    """
    live = batch.valid & (batch.types == EventType.CURRENT)
    order = stable_partition_order(live)
    cols = {k: v[order] for k, v in batch.cols.items()}
    ts = batch.ts[order]
    return cols, ts, jnp.sum(live.astype(jnp.int32)), order


def _gather_overall(
    ring_cols: dict,
    ring_ts: jax.Array,
    comp_cols: dict,
    comp_ts: jax.Array,
    appended0: jax.Array,
    o_idx: jax.Array,
):
    """Fetch events by overall arrival index: from the ring for pre-batch
    events, from the compacted batch for this batch's arrivals.

    NOTE: vectorized int64 `%`/`-` here is software-emulated on TPU (no
    native s64 ALU) — hot windows use `_gather_rel` instead, which keeps the
    per-lane math in int32 and only the scalar base in int64."""
    C = ring_ts.shape[0]
    B = comp_ts.shape[0]
    from_batch = o_idx >= appended0
    ring_slot = jnp.clip(o_idx, 0, None) % C
    batch_slot = jnp.clip(o_idx - appended0, 0, B - 1)
    cols = {
        k: jnp.where(from_batch, comp_cols[k][batch_slot], ring_cols[k][ring_slot])
        for k in ring_cols
    }
    ts = jnp.where(from_batch, comp_ts[batch_slot], ring_ts[ring_slot])
    return cols, ts


def _gather_rel(ring_cols, ring_ts, comp_cols, comp_ts, appended0, base, offs):
    """`_gather_overall` for o_idx = base + offs, with ALL per-lane arithmetic
    in int32: `base` is an int64 scalar (folded into two scalar reductions),
    `offs` an int32 vector. TPU v5e has no native s64 ALU — per-lane s64
    div/mod lowers to thousands of emulated ops — so the hot windows keep
    lane math 32-bit and reserve int64 for scalars and timestamp payloads."""
    C = ring_ts.shape[0]
    B = comp_ts.shape[0]
    # offset of the first batch arrival relative to base (|value| <= C+B)
    rel0 = (appended0 - base).astype(jnp.int32)
    from_batch = offs >= rel0
    batch_slot = jnp.clip(offs - rel0, 0, B - 1)
    # floored modulo wraps a negative base correctly (callers mask lanes
    # whose overall index is negative, but lanes at base+offs >= 0 with a
    # negative base are real ring rows and must hit their true slot)
    ring_base = (base % C).astype(jnp.int32)
    ring_slot = (ring_base + offs) % C
    cols = {
        k: jnp.where(from_batch, comp_cols[k][batch_slot], ring_cols[k][ring_slot])
        for k in ring_cols
    }
    ts = jnp.where(from_batch, comp_ts[batch_slot], ring_ts[ring_slot])
    return cols, ts


def _scatter_append(ring_cols, ring_ts, comp_cols, comp_ts, appended0, n_valid):
    """Write the batch's valid events into the ring at slot (appended0+p)%C.
    When more than C events arrive in one batch only the last C survive —
    earlier lanes are masked out so the scatter has no duplicate slots.
    Per-lane math is int32 (see `_gather_rel`)."""
    C = ring_ts.shape[0]
    B = comp_ts.shape[0]
    p = jnp.arange(B, dtype=jnp.int32)
    n_valid = n_valid.astype(jnp.int32)
    keep = (p < n_valid) & (p >= n_valid - C)
    base = (appended0 % C).astype(jnp.int32)
    slot = jnp.where(keep, (base + p) % C, C)  # C = drop sentinel
    new_cols = {k: ring_cols[k].at[slot].set(comp_cols[k], mode="drop")
                for k in ring_cols}
    new_ts = ring_ts.at[slot].set(comp_ts, mode="drop")
    return new_cols, new_ts


def _sort_chunk(keys, cols, ts, valid, types, width):
    """Order lanes by emission key (invalid lanes pushed to the end) and trim
    to `width` lanes.

    `keys` is either an int32 (hi, lo) pair — the fast path, sorted with a
    native two-key 32-bit comparator — or a single legacy array (extra
    windows; s64 keys sort via emulated two-word compares there)."""
    if isinstance(keys, tuple):
        hi, lo = keys
        hi = jnp.where(valid, hi, jnp.iinfo(jnp.int32).max)
        iota = jnp.arange(hi.shape[0], dtype=jnp.int32)
        _, _, order = jax.lax.sort((hi, lo, iota), num_keys=2, is_stable=True)
        order = order[:width]
    else:
        k = jnp.where(valid, keys, BIG)
        order = jnp.argsort(k, stable=True)[:width]
    return EventBatch(
        ts=ts[order],
        cols={n: v[order] for n, v in cols.items()},
        valid=valid[order],
        types=types[order],
    )


def _merge_order(keys, valids):
    """Global emission permutation for G lane groups whose VALID lanes
    already carry nondecreasing (hi, lo) keys — true for every window-chunk
    assembly (currents/RESETs/expireds are generated in emission order).

    INVARIANT (monotone-timestamp ingress): each group's valid-lane keys
    must be nondecreasing in lane order. Window emission keys derive from
    event timestamps/arrival order, and every ingress path guarantees
    monotone timestamps per junction (flush pads ts with the last value;
    the watermark never regresses — core/stream.py). Feeding a window
    out-of-order timestamps (e.g. externalTime over a disordered attribute
    clock) breaks the premise: the rank-merge scatter would silently
    drop/duplicate lanes where a comparator sort merely mis-ordered output.
    With `dtypes.config.debug_checks` (or SIDDHI_DEBUG_CHECKS=1) each
    group's key order is verified per step and violations warn loudly.

    Replaces the chunk comparator sort (XLA CPU: ~74 ms at 282k lanes) with
    per-group stable partitions + cross-group searchsorted rank sums
    (~2 ms): merged_rank(lane) = local_rank + Σ_h |{k in group h : k < key}|
    (≤ for groups ordered earlier, < for later — reproducing the stable
    concatenation order on ties). TPU also wins: no bitonic sort pass.
    Returns order over the CONCATENATED index space (valid lanes first, in
    key order; invalid lanes after, in concatenation order)."""
    G = len(keys)
    lens = [k[0].shape[0] for k in keys]
    total = sum(lens)
    offsets = [sum(lens[:g]) for g in range(G)]

    ck, orders, nvs = [], [], []
    for (hi, lo), v in zip(keys, valids):
        og = stable_partition_order(v)
        nv = jnp.sum(v.astype(jnp.int32))
        k = (hi.astype(jnp.int64) << 32) | lo.astype(jnp.uint32).astype(jnp.int64)
        k = k[og]
        k = jnp.where(jnp.arange(k.shape[0]) < nv, k, jnp.int64(BIG))
        ck.append(k)
        orders.append(og)
        nvs.append(nv)
    total_valid = sum(nvs)

    if dtypes.config.debug_checks:
        # partitioned keys end with a BIG suffix, so one pairwise compare
        # per group covers exactly the valid prefix
        ok = jnp.bool_(True)
        for k in ck:
            if k.shape[0] > 1:
                ok = ok & jnp.all(k[1:] >= k[:-1])
        jax.debug.callback(_warn_nonmonotone_keys, ok)

    order_all = jnp.zeros((total,), jnp.int32)
    inv_base = total_valid
    for g in range(G):
        iota = jnp.arange(lens[g], dtype=jnp.int32)
        rank = iota
        for h in range(G):
            if h == g:
                continue
            side = "right" if h < g else "left"
            rank = rank + searchsorted32(ck[h], ck[g], side=side)
        is_val = iota < nvs[g]
        rank = jnp.where(is_val, rank, inv_base + (iota - nvs[g]))
        inv_base = inv_base + (lens[g] - nvs[g])
        order_all = order_all.at[rank].set(offsets[g] + orders[g])
    return order_all


def _warn_nonmonotone_keys(ok) -> None:
    """Debug-checks callback: fires host-side per step (see _merge_order)."""
    if not bool(ok):
        import warnings
        warnings.warn(
            "window rank-merge received a group whose valid-lane emission "
            "keys are NOT nondecreasing — the monotone-timestamp ingress "
            "invariant is broken (out-of-order event/attribute clocks?); "
            "the scatter may drop or duplicate lanes. Fix the ingress "
            "ordering (docs/PARITY.md 'monotone-timestamp invariant')",
            stacklevel=2)


def _merge_sorted_chunks(keys, colss, tss, valids, types, width):
    """Rank-merged chunk assembly (see `_merge_order`)."""
    order = _merge_order(keys, valids)[:width]
    all_cols = {k: jnp.concatenate([c[k] for c in colss]) for k in colss[0]}
    all_ts = jnp.concatenate(tss)
    all_valid = jnp.concatenate(valids)
    all_types = jnp.concatenate(types)
    return EventBatch(
        ts=all_ts[order],
        cols={n: v[order] for n, v in all_cols.items()},
        valid=all_valid[order],
        types=all_types[order],
    )


def _empty_like_cols(layout: dict, n: int) -> dict:
    return {k: jnp.zeros((n,), dtype=dt) for k, dt in layout.items()}


class TypedLayout(dict):
    """Column layout (name -> device dtype) carrying the AttributeTypes
    behind it, for window factories that must distinguish STRING codes from
    raw ints (both int32 on device). Build via `make_layout`."""

    attr_types: dict


def make_layout(attr_types: dict) -> TypedLayout:
    """attr_types: name -> AttributeType (OBJECT already excluded)."""
    from ..core import dtypes as _dt
    lo = TypedLayout({n: _dt.device_dtype(t) for n, t in attr_types.items()})
    lo.attr_types = dict(attr_types)
    return lo


# --------------------------------------------------------------------------- #
# packed-row payload: all columns + ts as one u32 matrix
#
# TPU per-op overhead dominates these steps (profiled ~0.1 ms per gather/
# scatter fusion at 8-16k lanes); per-column rings cost one memory op per
# column per phase. Packing every column into one [*, W] u32 matrix makes
# ring append, candidate fetch, and the emission-sort gather ONE memory op
# each, independent of column count. 8-byte payloads (int64/f64 + ts) span
# two words; bitcasts/stacks fuse into neighbouring elementwise work.
# --------------------------------------------------------------------------- #


def _layout_words(layout: dict) -> int:
    """u32 words per packed row: columns in layout order, then 2 ts words."""
    n = 0
    for dt in layout.values():
        n += 1 if (jnp.dtype(dt) == jnp.bool_
                   or jnp.dtype(dt).itemsize == 4) else 2
    return n + 2


def _pack_rows(cols: dict, ts: jax.Array, layout: dict) -> jax.Array:
    """Pack columns + ts into a [W, L] u32 word matrix.

    TPU layout note: the LANE (minor) axis must be the long row axis — a
    [L, W] matrix with W ~ 4-8 pads the minor dim to 128 lanes, physically
    inflating a 100k-row ring ~20-30x and turning every ring copy into a
    multi-ms HBM burn. [W, L] keeps lanes fully packed."""
    words = []
    for name, dt in layout.items():
        a = cols[name]
        if a.dtype == jnp.bool_:
            words.append(a.astype(jnp.uint32))
        elif a.dtype.itemsize == 8:
            w = jax.lax.bitcast_convert_type(a, jnp.uint32)
            words.append(w[..., 0])
            words.append(w[..., 1])
        else:
            words.append(jax.lax.bitcast_convert_type(a, jnp.uint32))
    w = jax.lax.bitcast_convert_type(ts.astype(jnp.int64), jnp.uint32)
    words.append(w[..., 0])
    words.append(w[..., 1])
    return jnp.stack(words, axis=0)  # [W, L]


def _unpack_rows(mat: jax.Array, layout: dict) -> tuple[dict, jax.Array]:
    cols = {}
    i = 0
    for name, dt in layout.items():
        dt = jnp.dtype(dt)
        if dt == jnp.bool_:
            cols[name] = mat[i] != 0
            i += 1
        elif dt.itemsize == 8:
            cols[name] = jax.lax.bitcast_convert_type(
                jnp.stack([mat[i], mat[i + 1]], axis=-1), dt)
            i += 2
        else:
            cols[name] = jax.lax.bitcast_convert_type(mat[i], dt)
            i += 1
    ts = jax.lax.bitcast_convert_type(
        jnp.stack([mat[i], mat[i + 1]], axis=-1), jnp.int64)
    return cols, ts


def _packed_ts(mat: jax.Array) -> jax.Array:
    """The ts payload (last two words) of packed rows, as int64."""
    return jax.lax.bitcast_convert_type(
        jnp.stack([mat[-2], mat[-1]], axis=-1), jnp.int64)


def compact_packed(batch: EventBatch, layout: dict):
    """compact() producing one packed matrix: returns (mat[W,B], n_valid32).
    Lanes >= n_valid hold garbage."""
    live = batch.valid & (batch.types == EventType.CURRENT)
    mat = _pack_rows(batch.cols, batch.ts, layout)
    order = stable_partition_order(live)
    return mat[:, order], jnp.sum(live, dtype=jnp.int32)


def _append_packed(ring: jax.Array, comp_mat: jax.Array, appended0,
                   n_valid32) -> jax.Array:
    """Contiguous FIFO append of comp_mat[:, :n_valid] at ring lane
    appended0%C. Requires B <= C (callers size rings accordingly). No
    scatter: one doubled-ring copy + blend + dynamic-update-slice + head
    fold, all contiguous along the lane axis."""
    W, C = ring.shape
    B = comp_mat.shape[1]
    base = (appended0 % C).astype(jnp.int32)
    ext = jnp.concatenate([ring, ring[:, :B]], axis=1)  # [W, C+B]
    old = jax.lax.dynamic_slice(ext, (jnp.int32(0), base), (W, B))
    p = jnp.arange(B, dtype=jnp.int32)
    blend = jnp.where((p < n_valid32)[None, :], comp_mat, old)
    ext = jax.lax.dynamic_update_slice(ext, blend, (jnp.int32(0), base))
    # lanes written past C wrap to the head
    wrapped = (jnp.arange(B, dtype=jnp.int32) < base + B - C)[None, :]
    head = jnp.where(wrapped, ext[:, C:], ext[:, :B])
    return jnp.concatenate([head, ext[:, B:C]], axis=1)


def _fetch_rel_packed(ring: jax.Array, comp_mat: jax.Array, base_idx,
                      appended0, E: int) -> jax.Array:
    """Rows at overall indices base_idx + [0, E): from the ring for pre-batch
    rows, from the compacted batch for this batch's arrivals. Contiguous:
    two dynamic slices + one blend (the packed `_gather_rel`)."""
    W, C = ring.shape
    B = comp_mat.shape[1]
    base = (base_idx % C).astype(jnp.int32)
    ext = jnp.concatenate([ring, ring[:, :E]], axis=1)
    cand = jax.lax.dynamic_slice(ext, (jnp.int32(0), base), (W, E))
    rel0 = (appended0 - base_idx).astype(jnp.int32)  # first batch offset
    # align batch lanes so slice lane i reads comp_mat[:, i - rel0]. The
    # slice origin E - rel0 ranges over [0, E] (rel0 >= 0), so the padded
    # array needs 2E lanes: E leading zeros + comp + trailing zeros. Lanes
    # past the real batch read zeros but are masked by callers
    # (cand_exists), since pe >= rel0 + n_valid is beyond the window's end.
    pad_tail = max(E - B, 0)
    padded = jnp.concatenate(
        [jnp.zeros((W, E), jnp.uint32), comp_mat,
         jnp.zeros((W, pad_tail), jnp.uint32)], axis=1)
    start = jnp.clip(E - rel0, 0, E)
    bat = jax.lax.dynamic_slice(padded, (jnp.int32(0), start), (W, E))
    offs = jnp.arange(E, dtype=jnp.int32)
    return jnp.where((offs >= rel0)[None, :], bat, cand)


def _gather_chunk_packed(order, payload_mat, emit_ts, valid, types,
                         layout: dict) -> EventBatch:
    """Apply an emission permutation with ONE packed gather: payload +
    emit ts + (valid, type) meta ride a single [W+3, L] matrix."""
    ets = jax.lax.bitcast_convert_type(emit_ts.astype(jnp.int64), jnp.uint32)
    meta = (valid.astype(jnp.uint32)
            | (types.astype(jnp.uint32) << 1))
    W = payload_mat.shape[0]
    full = jnp.concatenate(
        [payload_mat, ets.T, meta[None, :]], axis=0)[:, order]
    cols, _stored_ts = _unpack_rows(full[:W], layout)
    emit = jax.lax.bitcast_convert_type(
        jnp.stack([full[W], full[W + 1]], axis=-1), jnp.int64)
    m = full[W + 2]
    return EventBatch(ts=emit, cols=cols,
                      valid=(m & 1) != 0,
                      types=(m >> 1).astype(jnp.int8))


def _sort_chunk_packed(hi, lo, payload_mat, emit_ts, valid, types,
                       layout: dict, width: int) -> EventBatch:
    """Emission-order sort (general, comparator-based) + packed gather.
    Window paths whose groups emit in key order use `_merge_order` +
    `_gather_chunk_packed` instead."""
    L = hi.shape[0]
    hi = jnp.where(valid, hi, jnp.iinfo(jnp.int32).max)
    iota = jnp.arange(L, dtype=jnp.int32)
    _, _, order = jax.lax.sort((hi, lo, iota), num_keys=2, is_stable=True)
    return _gather_chunk_packed(order[:width], payload_mat, emit_ts, valid,
                                types, layout)


def window_has_time_semantics(window: "WindowOp") -> bool:
    """True if the window needs heartbeats (empty timer batches) to emit
    expirations when no data arrives — the TPU analogue of the reference's
    Scheduler TIMER wiring (core/util/Scheduler.java:48)."""
    if getattr(window, "time_ms", None) is not None:
        return True
    if getattr(window, "needs_heartbeat", False):  # cron/hopping etc.
        return True
    return isinstance(window, (TimeBatchWindow, SessionWindow))


class WindowOp:
    """Base window operator. Subclasses define init_state/step; both must be
    traceable (called inside the query's jitted step)."""

    #: chunk width produced per step for a FULL-capacity batch (static upper
    #: bound — rate limiters size their rings from it)
    chunk_width: int
    #: True when step() derives the lane count from the incoming batch
    #: instead of the planned batch capacity — the window then accepts
    #: shape-bucketed (narrower) batches directly; runtimes pad batches
    #: back to full capacity for windows that bake their B
    shape_polymorphic = False

    def init_state(self):
        raise NotImplementedError

    def step(self, state, batch: EventBatch, now: jax.Array):
        raise NotImplementedError

    def contents(self, state, now: jax.Array):
        """Current in-window rows as (cols, ts, valid) over the ring — the
        FindableProcessor surface joins probe (reference:
        core/query/processor/stream/window/SlidingFindableWindowProcessor).
        Base: no findable contents."""
        raise SiddhiAppCreationError(
            f"window {type(self).__name__} is not findable (joins)")


def _ring_live_mask(ring_len: int, lo: jax.Array, hi: jax.Array):
    """Valid-slot mask for a ring holding overall indices [lo, hi): slot s's
    most recent write is idx = hi-1 - ((hi-1-s) % C); it is live iff >= lo."""
    s = jnp.arange(ring_len, dtype=jnp.int64)
    last_written = hi - 1 - ((hi - 1 - s) % ring_len)
    return (last_written >= 0) & (last_written >= lo) & (last_written < hi)


# --------------------------------------------------------------------------- #
# sliding windows (length, time, timeLength, delay)
# --------------------------------------------------------------------------- #


class SlidingState(NamedTuple):
    ring: jax.Array  # u32[W, C] packed rows (all columns + ts words)
    appended: jax.Array  # int64 total valid arrivals ever
    expired: jax.Array  # int64 total expirations ever
    wm: jax.Array  # int64 external-time watermark (externalTime mode only)
    overflow: jax.Array  # int64 lifetime live rows overwritten past capacity


class SlidingWindow(WindowOp):
    """Unified FIFO sliding window: length(N) and time(W) (and timeLength) are
    the same machine with different expiry rules. Events expire strictly in
    arrival order (timestamps are monotone per stream junction), so the window
    is always a contiguous [expired, appended) range of overall indices.

    Reference: LengthWindowProcessor.java:105-143, TimeWindowProcessor.java:133
    (scheduler-driven TIMER expiry becomes watermark-driven: the `now` scalar
    advances with each batch / heartbeat and flushes due expirations).
    """

    shape_polymorphic = True  # step() reads B from the batch (bucketing)

    def __init__(self, layout: dict, batch_cap: int, *,
                 length: Optional[int] = None,
                 time_ms: Optional[int] = None,
                 capacity: Optional[int] = None,
                 max_expired: Optional[int] = None,
                 is_delay: bool = False,
                 ts_attr: Optional[str] = None):
        self.layout = layout
        self.B = batch_cap
        self.length = length
        self.time_ms = time_ms
        self.is_delay = is_delay
        #: externalTime(tsAttr, W): expiry driven by an event attribute clock
        #: (reference: ExternalTimeWindowProcessor) instead of arrival time
        self.ts_attr = ts_attr
        #: @app:eventTime allowed lateness (set by the query runtime): the
        #: device watermark trails max-seen by this much so panes stay open
        #: for rows the ingress gate still buffers. Static Python attr — the
        #: default 0 keeps the traced jaxpr identical to the pre-lateness
        #: form (optimizer parity + SL204 fastpath certification)
        self.lateness_ms = 0
        # packed FIFO appends require B <= C (no last-C overwrite dance)
        if length is not None and time_ms is None:
            self.C = max(length, batch_cap, 1)
        else:
            self.C = max(capacity or dtypes.config.default_window_capacity,
                         batch_cap)
        self.E = max_expired if max_expired is not None else (
            batch_cap if (length is not None and time_ms is None) else max(batch_cap, 1024))
        # the packed candidate fetch slices E rows from a ring extended by E —
        # a ring smaller than E (tiny timeLength counts) would crash at trace
        # time or misalign once the base wraps
        self.C = max(self.C, self.E)
        self.chunk_width = self.B + self.E
        self.W = _layout_words(layout)

    def init_state(self) -> SlidingState:
        return SlidingState(
            ring=jnp.zeros((self.W, self.C), jnp.uint32),
            appended=jnp.int64(0),
            expired=jnp.int64(0),
            wm=jnp.int64(-(2**62)),
            overflow=jnp.int64(0),
        )

    def step(self, state: SlidingState, batch: EventBatch, now: jax.Array):
        # B is the INCOMING batch capacity (<= self.B under shape-bucketed
        # dispatch): every lane-count shape below derives from it, so one
        # window instance serves the whole bucket ladder (one trace per rung)
        B, E, C = batch.capacity, self.E, self.C
        comp_mat, n_valid32 = compact_packed(batch, self.layout)
        n_valid = n_valid32.astype(jnp.int64)

        if self.ts_attr is not None:
            # external clock: the time axis is an event attribute; the
            # watermark advances to the max attribute value seen. The packed
            # ts words are REPLACED by the attribute clock so ring rows carry
            # the expiry-relevant time.
            tcols, _ = _unpack_rows(comp_mat, self.layout)
            comp_ts = tcols[self.ts_attr].astype(jnp.int64)
            w = jax.lax.bitcast_convert_type(comp_ts, jnp.uint32)
            comp_mat = comp_mat.at[-2].set(w[..., 0]).at[-1].set(w[..., 1])
            mx = jnp.max(jnp.where(
                jnp.arange(B) < n_valid, comp_ts, jnp.int64(-(2**62))))
            if self.lateness_ms:
                # watermark-driven emission: trail max-seen by the allowed
                # lateness so panes close only once the ingress gate can no
                # longer release rows into them (deterministic regardless
                # of arrival order)
                mx = mx - jnp.int64(self.lateness_ms)
            wm = jnp.maximum(state.wm, mx)
            now = wm
        else:
            comp_ts = _packed_ts(comp_mat)
            wm = state.wm

        appended1 = state.appended + n_valid

        # ---- expiry candidates: the E oldest in-window events ----
        # One contiguous packed fetch (ring rows blended with batch rows);
        # per-lane index math stays int32 (s64 lane math is emulated on TPU).
        pe = jnp.arange(E, dtype=jnp.int32)
        win_len1 = (appended1 - state.expired).astype(jnp.int32)
        cand_exists = pe < win_len1
        cand_mat = _fetch_rel_packed(
            state.ring, comp_mat, state.expired, state.appended, E)
        cand_ts = _packed_ts(cand_mat)

        if self.time_ms is not None and self.length is None:
            # time(W): candidate expires once now >= cand_ts + W; the trigger
            # position is the first batch arrival with ts >= cand_ts + W (ties:
            # expire before processing the arrival), or end-of-batch if only
            # the final watermark covers it.
            deadline = cand_ts + jnp.int64(self.time_ms)
            trig = searchsorted32(
                jnp.where(jnp.arange(B) < n_valid, comp_ts, BIG), deadline,
                side="left")
            expires = cand_exists & (deadline <= now)
            emit_ts = deadline
        elif self.time_ms is None:
            # length(N): candidate o is evicted by arrival with overall index
            # o + N (the N+1'th event); trigger position within this batch:
            # trig = (expired + pe + N) - appended, all relative → int32.
            rel = (state.expired + jnp.int64(self.length)
                   - state.appended).astype(jnp.int32)
            trig = pe + rel
            expires = cand_exists & (trig < n_valid32)
            # reference stamps evicted events with current time
            # (LengthWindowProcessor.java:121)
            safe_trig = jnp.clip(trig, 0, B - 1)
            emit_ts = comp_ts[safe_trig]
        else:
            # timeLength(W, N): expire on whichever rule fires first.
            deadline = cand_ts + jnp.int64(self.time_ms)
            trig_time = searchsorted32(
                jnp.where(jnp.arange(B) < n_valid, comp_ts, BIG), deadline,
                side="left")
            rel = (state.expired + jnp.int64(self.length)
                   - state.appended).astype(jnp.int32)
            trig_len = pe + rel
            time_fires = deadline <= now
            len_fires = trig_len < n_valid32
            trig = jnp.where(
                time_fires & len_fires, jnp.minimum(trig_time, trig_len),
                jnp.where(time_fires, trig_time, trig_len))
            expires = cand_exists & (time_fires | len_fires)
            safe_trig = jnp.clip(trig, 0, B - 1)
            emit_ts = jnp.where(
                time_fires & (trig_time <= trig_len), deadline, comp_ts[safe_trig])

        n_expired_new = jnp.sum(expires.astype(jnp.int64))
        # Expirations are FIFO: `expires` is a prefix of candidates by
        # construction for length windows; for time windows with monotone ts
        # it is also a prefix. (Non-prefix would indicate ts disorder.)

        # ---- assemble chunk: E expired lanes + B current lanes ----
        p = jnp.arange(B, dtype=jnp.int32)
        cur_valid = p < n_valid32

        keys_exp = jnp.clip(trig, 0, B) * 4 + KIND_EXPIRED
        keys_cur = p * 4 + KIND_CURRENT

        all_hi = jnp.concatenate([keys_exp, keys_cur])
        all_lo = jnp.concatenate([pe, p])
        all_mat = jnp.concatenate([cand_mat, comp_mat], axis=1)
        all_emit = jnp.concatenate([emit_ts, comp_ts])
        all_valid = jnp.concatenate([expires, cur_valid])
        all_types = jnp.concatenate([
            jnp.full((E,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.CURRENT, jnp.int8),
        ])

        if self.is_delay:
            # delay(W): expired lanes are re-emitted as CURRENT after the
            # delay; arrivals are swallowed (reference DelayWindowProcessor).
            all_types = jnp.full((E + B,), EventType.CURRENT, jnp.int8)
            all_valid = jnp.concatenate([expires, jnp.zeros((B,), bool)])
            exp_v, cur_v = expires, jnp.zeros((B,), bool)
        else:
            exp_v, cur_v = expires, cur_valid

        # both groups emit in nondecreasing (hi, lo) order (expiry triggers
        # follow candidate age; currents follow arrival): rank-merge
        order = _merge_order([(keys_exp, pe), (keys_cur, p)],
                             [exp_v, cur_v])[:B + E]
        chunk = _gather_chunk_packed(order, all_mat, all_emit, all_valid,
                                     all_types, self.layout)

        # ---- ring update ----
        new_ring = _append_packed(state.ring, comp_mat, state.appended,
                                  n_valid32)

        # live rows overwritten by ring wrap (a time window holding more
        # than C un-expired rows): new excess this step, monotone
        expired1 = state.expired + n_expired_new
        over0 = jnp.maximum(state.appended - state.expired - self.C, 0)
        over1 = jnp.maximum(appended1 - expired1 - self.C, 0)
        new_state = SlidingState(
            ring=new_ring,
            appended=appended1,
            expired=expired1,
            wm=wm,
            overflow=state.overflow + jnp.maximum(over1 - over0, 0),
        )
        return new_state, chunk

    def contents(self, state: SlidingState, now: jax.Array):
        ring_cols, ring_ts = _unpack_rows(state.ring, self.layout)
        live = _ring_live_mask(self.C, state.expired, state.appended)
        if self.time_ms is not None:
            # probe-time expiry: rows past their deadline are out even if no
            # batch has flushed them yet
            live = live & (ring_ts + jnp.int64(self.time_ms) > now)
        return ring_cols, ring_ts, live


# --------------------------------------------------------------------------- #
# batch (tumbling) windows: lengthBatch, timeBatch, batch
# --------------------------------------------------------------------------- #


class BatchState(NamedTuple):
    ring_cols: dict
    ring_ts: jax.Array
    appended: jax.Array  # int64 total arrivals
    flushed: jax.Array  # int64 arrivals already emitted (flush boundary)
    prev_start: jax.Array  # int64 start overall idx of the previous flush
    epoch_base: jax.Array  # int64 ts base for time flushes (first-event ts)
    has_base: jax.Array  # bool
    wm: jax.Array  # int64 external-time watermark (externalTimeBatch only)


class LengthBatchWindow(WindowOp):
    """lengthBatch(N): tumbling count window. At each flush boundary emits
    [expired lanes of the previous flush, RESET, N current lanes]
    (reference: LengthBatchWindowProcessor.java:210-243)."""

    def __init__(self, layout: dict, batch_cap: int, length: int,
                 expired_on: bool = True):
        if length <= 0:
            raise SiddhiAppCreationError("lengthBatch length must be > 0")
        self.layout = layout
        self.B = batch_cap
        self.N = length
        self.expired_on = expired_on
        self.C = 2 * length + batch_cap  # holds prev flush + partial + batch
        max_flushes = batch_cap // length + 2
        width = batch_cap + length  # current lanes possible
        if expired_on:
            width += batch_cap + length  # expired lanes
        width += max_flushes  # RESET lanes
        self.chunk_width = width
        self._max_flushes = max_flushes

    def init_state(self) -> BatchState:
        return BatchState(
            ring_cols=_empty_like_cols(self.layout, self.C),
            ring_ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            appended=jnp.int64(0),
            flushed=jnp.int64(0),
            prev_start=jnp.int64(-1),
            epoch_base=jnp.int64(0),
            has_base=jnp.bool_(False),
            wm=jnp.int64(-(2**62)),
        )

    def step(self, state: BatchState, batch: EventBatch, now: jax.Array):
        B, N, C = self.B, self.N, self.C
        Nl = jnp.int64(N)
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        appended1 = state.appended + n_valid

        f_done = state.flushed // Nl  # flushes completed before this batch
        f_now = appended1 // Nl  # flushes completed after this batch
        # All per-lane index math below is int32 RELATIVE to state.flushed
        # (int64 scalars only feed scalar subtractions) — vectorized s64
        # div/mod is software-emulated on TPU and was the step's hot spot.
        # Invariant: state.flushed == f_done*N exactly, so for offset p:
        #   (flushed+p) // N = f_done + p//N,  (flushed+p) % N = p % N.
        nf = (f_now - f_done).astype(jnp.int32)  # flushes completing now
        r0 = (state.appended - state.flushed).astype(jnp.int32)  # partial len

        # completion position (within this batch) of flush f: arrival index of
        # the flush's last event = (f+1)*N - 1 - appended0
        # Candidate currents: overall indices [flushed, f_now*N)
        cur_count_max = B + N
        p_cur = jnp.arange(cur_count_max, dtype=jnp.int32)
        cur_exists = p_cur < nf * N
        cur_cols, cur_ts = _gather_rel(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, state.flushed, p_cur)
        cur_flush_rel = p_cur // N
        cur_comp = (cur_flush_rel + 1) * N - 1 - r0  # batch pos of flush end
        cur_keys = _emit_key(cur_comp, KIND_CURRENT, p_cur % N, B)

        # RESET lanes: one per completing flush
        MF = self._max_flushes
        f_rel = jnp.arange(MF, dtype=jnp.int32)
        reset_exists = f_rel < nf
        reset_comp = (f_rel + 1) * N - 1 - r0
        reset_keys = _emit_key(reset_comp, KIND_RESET,
                               jnp.zeros((MF,), jnp.int32), B)
        reset_cols = _empty_like_cols(self.layout, MF)
        safe_rc = jnp.clip(reset_comp, 0, B - 1)
        reset_ts = comp_ts[safe_rc]

        keys = [cur_keys, reset_keys]
        colss = [cur_cols, reset_cols]
        tss = [cur_ts, reset_ts]
        valids = [cur_exists, reset_exists]
        types = [jnp.full((cur_count_max,), EventType.CURRENT, jnp.int8),
                 jnp.full((MF,), EventType.RESET, jnp.int8)]

        if self.expired_on:
            # expired lanes: events of flush f-1 re-emitted when flush f
            # completes (only if a previous flush exists); base (f_done-1)*N
            p_exp = jnp.arange(cur_count_max, dtype=jnp.int32)
            exp_flush_rel = p_exp // N - 1  # relative to f_done
            # event of flush f is re-emitted as expired when flush f+1
            # completes. o_exp >= 0 ⟺ f_done >= 1 or p >= N (two flushes
            # completing inside the very first batch).
            exp_exists = ((f_done >= 1) | (p_exp >= N)) & (
                (exp_flush_rel + 1) < nf)
            exp_cols, exp_ts_orig = _gather_rel(
                state.ring_cols, state.ring_ts, comp_cols, comp_ts,
                state.appended, (f_done - 1) * Nl, p_exp)
            exp_comp = (exp_flush_rel + 2) * N - 1 - r0
            exp_keys = _emit_key(exp_comp, KIND_EXPIRED, p_exp % N, B)
            safe_ec = jnp.clip(exp_comp, 0, B - 1)
            exp_ts = comp_ts[safe_ec]  # reference re-stamps with current time
            keys.append(exp_keys)
            colss.append(exp_cols)
            tss.append(exp_ts)
            valids.append(exp_exists)
            types.append(jnp.full((cur_count_max,), EventType.EXPIRED, jnp.int8))

        chunk = _merge_sorted_chunks(keys, colss, tss, valids, types,
                                     self.chunk_width)

        new_ring_cols, new_ring_ts = _scatter_append(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, n_valid)
        new_state = BatchState(
            ring_cols=new_ring_cols,
            ring_ts=new_ring_ts,
            appended=appended1,
            flushed=f_now * Nl,
            prev_start=(f_now - 1) * Nl,
            epoch_base=state.epoch_base,
            has_base=state.has_base,
            wm=state.wm,
        )
        return new_state, chunk

    def contents(self, state: BatchState, now: jax.Array):
        """Joins see the accumulating (unflushed) bucket (reference:
        BatchingFindableWindowProcessor over the current batch buffer)."""
        live = _ring_live_mask(self.C, state.flushed, state.appended)
        return state.ring_cols, state.ring_ts, live


def _emit_key(comp_pos, kind, within, B):
    """Emission sort key pair: hi = (completion batch position, kind),
    lo = within-flush sequence. Both int32 — sorted with a native two-key
    comparator instead of one emulated-s64 key (see `_sort_chunk`)."""
    hi = jnp.clip(comp_pos, -1, B).astype(jnp.int32) * 4 + kind
    return hi, within.astype(jnp.int32)


class TimeBatchWindow(WindowOp):
    """timeBatch(W): tumbling time window. Buckets are [base + k*W, base +
    (k+1)*W); a bucket flushes when an arrival or the watermark crosses its end
    (reference: TimeBatchWindowProcessor — scheduler-driven flush becomes
    watermark-driven). Emits [expired(prev bucket), RESET, currents] like
    lengthBatch."""

    def __init__(self, layout: dict, batch_cap: int, time_ms: int,
                 capacity: Optional[int] = None, expired_on: bool = True,
                 start_time: Optional[int] = None,
                 ts_attr: Optional[str] = None):
        self.layout = layout
        self.B = batch_cap
        self.W = time_ms
        self.expired_on = expired_on
        self.start_time = start_time
        #: externalTimeBatch(tsAttr, W): bucket clock from an event attribute
        #: (reference: ExternalTimeBatchWindowProcessor)
        self.ts_attr = ts_attr
        #: @app:eventTime allowed lateness (set by the query runtime) — see
        #: SlidingWindow.lateness_ms: buckets flush only once the trailing
        #: watermark crosses their end; 0 keeps the jaxpr unchanged
        self.lateness_ms = 0
        self.C = capacity or max(dtypes.config.default_window_capacity, 2 * batch_cap)
        self.E = max(batch_cap, 1024)  # max emitted current/expired lanes per step
        width = self.E + 1 + (self.E if expired_on else 0)
        self.chunk_width = width

    def init_state(self) -> BatchState:
        return BatchState(
            ring_cols=_empty_like_cols(self.layout, self.C),
            ring_ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            appended=jnp.int64(0),
            flushed=jnp.int64(0),
            prev_start=jnp.int64(0),
            epoch_base=jnp.int64(self.start_time if self.start_time is not None else 0),
            has_base=jnp.bool_(self.start_time is not None),
            wm=jnp.int64(-(2**62)),
        )

    def step(self, state: BatchState, batch: EventBatch, now: jax.Array):
        B, E, C = self.B, self.E, self.C
        W = jnp.int64(self.W)
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        if self.ts_attr is not None:
            comp_ts = comp_cols[self.ts_attr].astype(jnp.int64)
            mx = jnp.max(jnp.where(
                jnp.arange(B) < n_valid, comp_ts, jnp.int64(-(2**62))))
            if self.lateness_ms:
                # hold the bucket open until the watermark (max-seen minus
                # allowed lateness) passes its end — the ingress gate may
                # still release rows belonging to it
                mx = mx - jnp.int64(self.lateness_ms)
            wm = jnp.maximum(state.wm, mx)
            now = wm
        else:
            wm = state.wm
        appended1 = state.appended + n_valid

        # establish bucket base from the first-ever event
        first_ts = jnp.where(n_valid > 0, comp_ts[0], now)
        base = jnp.where(state.has_base, state.epoch_base, first_ts)
        has_base = state.has_base | (n_valid > 0)

        # Buckets are computed RELATIVE to now's bucket, in int32: one scalar
        # s64 division for now_bucket, then per-lane (ts - pivot) clamped into
        # int32 and divided by W as int32 (vectorized s64 division is
        # software-emulated on TPU and dominated this step's cost). Events
        # more than ~12 days (2^30 ms) from the watermark collapse onto the
        # extreme bucket — ordering/flush decisions stay monotone-correct.
        # DOCUMENTED DIVERGENCE: if one micro-batch spans >2^30 ms (e.g.
        # historical replay with a huge watermark jump), distinct NON-empty
        # far-past buckets merge into one flush group — one RESET and merged
        # per-bucket aggregates where the reference emits separate batches.
        # Events this far apart never share a micro-batch in live streams.
        now_bucket = (now - base) // W  # scalar
        pivot = base + now_bucket * W  # scalar; bucket(pivot) == now_bucket
        LIM = jnp.int64(1 << 30)
        W32 = jnp.int32(self.W) if self.W < (1 << 31) else None

        def bucket_rel(ts):  # → int32 bucket index relative to now's bucket
            d = jnp.clip(ts - pivot, -LIM, LIM)
            if W32 is None:  # window ≥ 2^31 ms: keep the emulated s64 path
                return (d // W).astype(jnp.int32)
            return d.astype(jnp.int32) // W32

        arr_bucket = bucket_rel(comp_ts)
        # final flushed bucket boundary: all buckets < flush_hi are emitted
        flush_hi = jnp.where(has_base, jnp.int32(0), jnp.int32(-(1 << 30)))

        # candidate currents: pending events [flushed, appended1) whose bucket
        # flushes this step. Per-lane offsets are int32 (see _gather_rel).
        pe = jnp.arange(E, dtype=jnp.int32)
        cur_exists_idx = pe < (appended1 - state.flushed).astype(jnp.int32)
        cur_cols, cur_ts = _gather_rel(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, state.flushed, pe)
        cur_bucket = bucket_rel(cur_ts)
        cur_emit = cur_exists_idx & (cur_bucket < flush_hi)
        # trigger position: first arrival in a later bucket
        I32MAX = jnp.iinfo(jnp.int32).max
        padded_buckets = jnp.where(jnp.arange(B) < n_valid, arr_bucket, I32MAX)
        trig = searchsorted32(padded_buckets, cur_bucket + 1, side="left")
        cur_keys = _emit_key(trig, KIND_CURRENT, pe, B)

        # RESET: one per flushed bucket — approximate with one reset per step
        # boundary between buckets (sufficient: grouped_scan's reset zeroes all
        # keys; consecutive empty buckets collapse into one reset).
        # reset fires right after the last current of each flushed bucket; we
        # emit a reset lane per candidate position where the *next* candidate
        # is in a different bucket.
        next_bucket = jnp.concatenate([cur_bucket[1:], jnp.full((1,), -1, jnp.int32)])
        is_bucket_end = cur_emit & ((next_bucket != cur_bucket) | ~jnp.concatenate(
            [cur_emit[1:], jnp.zeros((1,), bool)]))
        reset_keys = _emit_key(trig, KIND_RESET, pe, B)
        reset_cols = _empty_like_cols(self.layout, E)
        reset_ts = cur_ts

        keys = [cur_keys, reset_keys]
        colss = [cur_cols, reset_cols]
        tss = [cur_ts, reset_ts]
        valids = [cur_emit, is_bucket_end]
        types = [jnp.full((E,), EventType.CURRENT, jnp.int8),
                 jnp.full((E,), EventType.RESET, jnp.int8)]

        if self.expired_on:
            # previous flushed bucket's events re-emitted as expired when the
            # next bucket flushes: events in [prev_start, flushed)
            exp_cols, exp_ts0 = _gather_rel(
                state.ring_cols, state.ring_ts, comp_cols, comp_ts,
                state.appended, state.prev_start, pe)
            exp_bucket = bucket_rel(exp_ts0)
            exp_emit = (pe < (state.flushed - state.prev_start).astype(jnp.int32)) & (
                exp_bucket + 1 < flush_hi)
            trig_e = searchsorted32(padded_buckets, exp_bucket + 2,
                                    side="left")
            exp_keys = _emit_key(trig_e, KIND_EXPIRED, pe, B)
            keys.append(exp_keys)
            colss.append(exp_cols)
            tss.append(exp_ts0)
            valids.append(exp_emit)
            types.append(jnp.full((E,), EventType.EXPIRED, jnp.int8))

        chunk = _merge_sorted_chunks(keys, colss, tss, valids, types,
                                     self.chunk_width)

        n_emitted = jnp.sum(cur_emit.astype(jnp.int64))
        new_flushed = state.flushed + n_emitted
        new_ring_cols, new_ring_ts = _scatter_append(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, n_valid)
        new_state = BatchState(
            ring_cols=new_ring_cols,
            ring_ts=new_ring_ts,
            appended=appended1,
            flushed=new_flushed,
            prev_start=jnp.where(n_emitted > 0, state.flushed, state.prev_start),
            epoch_base=base,
            has_base=has_base,
            wm=wm,
        )
        return new_state, chunk

    def contents(self, state: BatchState, now: jax.Array):
        live = _ring_live_mask(self.C, state.flushed, state.appended)
        return state.ring_cols, state.ring_ts, live


# --------------------------------------------------------------------------- #
# pass-through (no window)
# --------------------------------------------------------------------------- #


class PassThroughWindow(WindowOp):
    """No window: batch lanes flow through as CURRENT (the query still gets
    chunk semantics so the selector path is uniform)."""

    shape_polymorphic = True  # step() is the identity — any lane count

    def __init__(self, layout: dict, batch_cap: int):
        self.layout = layout
        self.B = batch_cap
        self.chunk_width = batch_cap

    def init_state(self):
        return ()

    def step(self, state, batch: EventBatch, now: jax.Array):
        return state, batch

    def contents(self, state, now: jax.Array):
        """A windowless join side holds nothing (reference: a bare stream in a
        join keeps a zero-length window — only the arriving event matches)."""
        cols = {k: jnp.zeros((1,), dtype=dt) for k, dt in self.layout.items()}
        return cols, jnp.zeros((1,), dtypes.TS_DTYPE), jnp.zeros((1,), bool)


# --------------------------------------------------------------------------- #
# session window
# --------------------------------------------------------------------------- #


class SessionState(NamedTuple):
    ring_cols: dict
    ring_ts: jax.Array
    ring_session: jax.Array  # int64 session id per ring slot
    appended: jax.Array
    flushed: jax.Array
    last_ts: jax.Array  # ts of latest arrival (gap detection)
    session: jax.Array  # current session id
    has_events: jax.Array  # bool


class SessionWindow(WindowOp):
    """session(gap): events pass through as CURRENT immediately; when a gap
    larger than `gap` opens (next arrival or watermark), the closed session's
    events are re-emitted as EXPIRED (reference: SessionWindowProcessor.java —
    current chunk passes through:308, expired chunk of the previous session
    prepended on rollover:303-307). Keyed sessions (`session(gap, key)`)
    live in ops/windows_extra.py KeyedSessionWindow."""

    def __init__(self, layout: dict, batch_cap: int, gap_ms: int,
                 capacity: Optional[int] = None):
        self.layout = layout
        self.B = batch_cap
        self.gap = gap_ms
        self.C = capacity or max(dtypes.config.default_window_capacity,
                                 2 * batch_cap)
        self.E = max(batch_cap, 1024)
        self.chunk_width = self.B + self.E

    def init_state(self) -> SessionState:
        return SessionState(
            ring_cols=_empty_like_cols(self.layout, self.C),
            ring_ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            ring_session=jnp.zeros((self.C,), jnp.int64),
            appended=jnp.int64(0),
            flushed=jnp.int64(0),
            last_ts=jnp.int64(0),
            session=jnp.int64(0),
            has_events=jnp.bool_(False),
        )

    def step(self, state: SessionState, batch: EventBatch, now: jax.Array):
        B, E, C = self.B, self.E, self.C
        gap = jnp.int64(self.gap)
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        p = jnp.arange(B, dtype=jnp.int64)
        is_arr = p < n_valid

        # gap break before arrival i (vs previous arrival / state.last_ts)
        prev_ts = jnp.concatenate([state.last_ts[None], comp_ts[:-1]])
        brk = is_arr & state.has_events & (comp_ts - prev_ts > gap)
        # the very first arrival ever starts session 0 without a break
        brk = brk & ~((p == 0) & ~state.has_events)
        arr_session = state.session + jnp.cumsum(brk.astype(jnp.int64))
        session_after = jnp.where(n_valid > 0, arr_session[jnp.clip(n_valid - 1, 0, B - 1)],
                                  state.session)
        # watermark close: gap elapsed since the last event of the batch
        new_last = jnp.where(n_valid > 0, comp_ts[jnp.clip(n_valid - 1, 0, B - 1)],
                             state.last_ts)
        wm_close = state.has_events | (n_valid > 0)
        wm_close = wm_close & (now - new_last > gap)
        session_open = jnp.where(wm_close, session_after + 1, session_after)

        # ---- currents pass through ----
        keys_cur = p * 4 + KIND_CURRENT
        # ---- expired: ring events whose session < session_open ----
        o = state.flushed + jnp.arange(E, dtype=jnp.int64)
        in_ring = o < state.appended
        slot = jnp.clip(o, 0, None) % C
        ring_sess = state.ring_session[slot]
        exp_ring = in_ring & (ring_sess < session_open)
        # batch arrivals whose session closed within this same step
        exp_arr = is_arr & (arr_session < session_open)
        # trigger position: first arrival of a later session (or end of batch)
        arr_sess_padded = jnp.where(is_arr, arr_session, BIG)
        trig_ring = searchsorted32(arr_sess_padded, ring_sess + 1,
                                   side="left").astype(jnp.int64)
        trig_arr = searchsorted32(arr_sess_padded, arr_session + 1,
                                  side="left").astype(jnp.int64)
        keys_exp_ring = jnp.clip(trig_ring, 0, jnp.int64(B)) * 4 + KIND_EXPIRED
        keys_exp_arr = jnp.clip(trig_arr, 0, jnp.int64(B)) * 4 + KIND_EXPIRED

        all_keys = jnp.concatenate([keys_exp_ring, keys_exp_arr, keys_cur])
        all_cols = {k: jnp.concatenate([state.ring_cols[k][slot], comp_cols[k],
                                        comp_cols[k]])
                    for k in self.layout}
        all_ts = jnp.concatenate([state.ring_ts[slot], comp_ts, comp_ts])
        all_valid = jnp.concatenate([exp_ring, exp_arr, is_arr])
        all_types = jnp.concatenate([
            jnp.full((E,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.CURRENT, jnp.int8),
        ])
        chunk = _sort_chunk(all_keys, all_cols, all_ts, all_valid, all_types,
                            self.chunk_width)

        # ---- ring update: append arrivals; account flushed ----
        new_cols, new_ts = _scatter_append(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, n_valid)
        wslot = jnp.where(is_arr, (state.appended + p) % C, C)
        new_sess = state.ring_session.at[wslot].set(arr_session, mode="drop")
        n_flushed_ring = jnp.sum(exp_ring.astype(jnp.int64))
        n_flushed_arr = jnp.sum(exp_arr.astype(jnp.int64))
        new_state = SessionState(
            ring_cols=new_cols, ring_ts=new_ts, ring_session=new_sess,
            appended=state.appended + n_valid,
            flushed=state.flushed + n_flushed_ring + n_flushed_arr,
            last_ts=new_last,
            session=session_open,
            has_events=state.has_events | (n_valid > 0),
        )
        return new_state, chunk

    def contents(self, state: SessionState, now: jax.Array):
        live = _ring_live_mask(self.C, state.flushed, state.appended)
        return state.ring_cols, state.ring_ts, live


# --------------------------------------------------------------------------- #
# sort window
# --------------------------------------------------------------------------- #


class SortState(NamedTuple):
    cols: dict
    ts: jax.Array
    seq: jax.Array  # int64 arrival order (stable tiebreak)
    valid: jax.Array
    count: jax.Array  # int64 arrivals ever


class SortWindow(WindowOp):
    """sort(N, attr [,'asc'|'desc'], ...): keeps the N best events by sort
    key; each arrival emits [current, evicted-worst as EXPIRED] (reference:
    SortWindowProcessor.java:151-181). Batch form: merge buffer+batch, keep
    the N best; evicted set matches the reference's per-event processing
    (the kept set after any arrival order is the N best)."""

    def __init__(self, layout: dict, batch_cap: int, n: int,
                 sort_keys: list):  # [(attr, +1|-1)]
        self.layout = layout
        self.B = batch_cap
        self.N = n
        self.sort_keys = sort_keys
        self.chunk_width = batch_cap + batch_cap + n  # currents + evictable
        self.M = self.N + self.B  # merge width

    def init_state(self) -> SortState:
        N = self.N
        return SortState(
            cols=_empty_like_cols(self.layout, N),
            ts=jnp.zeros((N,), dtypes.TS_DTYPE),
            seq=jnp.zeros((N,), jnp.int64),
            valid=jnp.zeros((N,), bool),
            count=jnp.int64(0),
        )

    def _rank_key(self, cols: dict, valid: jax.Array):
        """Composite sort rank via successive stable argsorts (last key first);
        invalid lanes sort last."""
        M = valid.shape[0]
        perm = jnp.arange(M)
        for attr, order in reversed(self.sort_keys):
            k = cols[attr][perm].astype(jnp.float64)
            k = jnp.where(order < 0, -k, k)
            perm = perm[jnp.argsort(k, stable=True)]
        k = jnp.where(valid[perm], 0, 1)
        perm = perm[jnp.argsort(k, stable=True)]
        return perm  # positions in best-to-worst order

    def step(self, state: SortState, batch: EventBatch, now: jax.Array):
        B, N = self.B, self.N
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        p = jnp.arange(B, dtype=jnp.int64)
        is_arr = p < n_valid

        m_cols = {k: jnp.concatenate([state.cols[k], comp_cols[k]])
                  for k in self.layout}
        m_ts = jnp.concatenate([state.ts, comp_ts])
        m_seq = jnp.concatenate([state.seq, state.count + p])
        m_valid = jnp.concatenate([state.valid, is_arr])

        from .groupby import invert_permutation
        perm = self._rank_key(m_cols, m_valid)
        keep_rank = invert_permutation(perm)
        kept = m_valid & (keep_rank < N)
        evicted = m_valid & ~kept

        # chunk: currents (arrival order) then evicted as EXPIRED
        keys_cur = p * 4 + KIND_CURRENT
        M = self.N + B
        keys_ev = jnp.full((M,), jnp.int64(B) * 4 + KIND_EXPIRED)
        all_keys = jnp.concatenate([keys_cur, keys_ev])
        all_cols = {k: jnp.concatenate([comp_cols[k], m_cols[k]])
                    for k in self.layout}
        all_ts = jnp.concatenate([comp_ts, jnp.full((M,), 0, dtypes.TS_DTYPE) + now])
        all_valid = jnp.concatenate([is_arr, evicted])
        all_types = jnp.concatenate([
            jnp.full((B,), EventType.CURRENT, jnp.int8),
            jnp.full((M,), EventType.EXPIRED, jnp.int8),
        ])
        chunk = _sort_chunk(all_keys, all_cols, all_ts, all_valid, all_types,
                            self.chunk_width)

        # new buffer: the N best lanes
        sel = perm[:N]
        new_state = SortState(
            cols={k: m_cols[k][sel] for k in self.layout},
            ts=m_ts[sel],
            seq=m_seq[sel],
            valid=m_valid[sel],
            count=state.count + n_valid,
        )
        return new_state, chunk

    def contents(self, state: SortState, now: jax.Array):
        return state.cols, state.ts, state.valid
