"""General expression / expressionBatch windows — exact reference semantics
for ARBITRARY retain conditions (VERDICT r3 item 3).

Reference: ExpressionWindowProcessor.java:204-234 (processStreamEvent): each
arrival is appended, the condition is evaluated over (current, first, last)
with running window aggregates; while it is false the window pops from the
front — `current` rebinding to the just-popped event — until it turns true
or the window empties. ExpressionBatchWindowProcessor.java:288-347: events
accumulate while the condition holds (evaluated INCLUDING the arrival); when
it breaks, the accumulated window flushes as a batch (expired copies of the
previous flush first), and the triggering event either joins the flush
(`includeTriggeringEvent=true`) or starts the next window.

TPU mapping: conditions reference only prefix-computable window metrics —
count(), sum/avg/stdDev(attr), first.attr / last.attr / bare attr (current),
eventTimestamp(first|last) — so the per-check evaluation is O(1) gathers
into arrival-order metric sequences + prefix-sum arrays. The sliding pop
loop is a `lax.while_loop` (each iteration advances either the arrival
cursor or the pop cursor: <= 2B + E iterations per step); expressionBatch
needs exactly one check per arrival, a `lax.scan`. Monotone-suffix
conditions keep the fully-vectorized binary-search path
(ops/expression_window.py) — this module is the exact fallback for
everything else.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    MathExpression,
    MathOp,
    Not,
    Or,
    Variable,
)
from .search import searchsorted32
from .windows import (
    KIND_CURRENT,
    KIND_EXPIRED,
    KIND_RESET,
    SlidingState,
    WindowOp,
    _append_packed,
    _fetch_rel_packed,
    _layout_words,
    _ring_live_mask,
    _sort_chunk_packed,
    _unpack_rows,
    compact_packed,
)
from .expression_window import ExpressionWindow

#: sentinel attr name for the timestamp payload
TS_ATTR = "\x00ts"


class _Terms(NamedTuple):
    attrs: frozenset  # attrs needing value sequences (incl. TS_ATTR)
    prefix_attrs: frozenset  # attrs needing prefix sums (sum/avg/stdDev)
    sq_attrs: frozenset  # attrs needing squared prefix sums (stdDev)


def _collect_terms(expr: Expression, layout: dict) -> _Terms:
    attrs, prefix, sq = set(), set(), set()

    def walk(e: Expression):
        if isinstance(e, (And, Or)):
            walk(e.left), walk(e.right)
        elif isinstance(e, Not):
            walk(e.expression)
        elif isinstance(e, Compare):
            walk(e.left), walk(e.right)
        elif isinstance(e, MathExpression):
            walk(e.left), walk(e.right)
        elif isinstance(e, Constant):
            if e.type_name == "string":
                raise SiddhiAppCreationError(
                    "expression window conditions cannot compare string "
                    "constants (dictionary codes are not orderable); filter "
                    "strings in the query instead")
        elif isinstance(e, Variable):
            if e.stream_id not in (None, "first", "last", "current"):
                raise SiddhiAppCreationError(
                    f"expression window variable {e.stream_id}.{e.attribute}"
                    " — only bare (current), first.* and last.* references "
                    "are available inside a window condition")
            if e.attribute not in layout:
                raise SiddhiAppCreationError(
                    f"expression window references unknown attribute "
                    f"{e.attribute!r}")
            attrs.add(e.attribute)
        elif isinstance(e, AttributeFunction):
            name = e.name
            if name == "count" and not e.parameters:
                return
            if name == "eventTimestamp":
                if e.parameters:
                    p = e.parameters[0]
                    if not (isinstance(p, Variable)
                            and p.attribute in ("first", "last")):
                        raise SiddhiAppCreationError(
                            "eventTimestamp() takes first or last")
                attrs.add(TS_ATTR)
                return
            if name in ("sum", "avg", "stdDev", "stddev"):
                p = e.parameters[0] if e.parameters else None
                if not isinstance(p, Variable) or p.attribute not in layout:
                    raise SiddhiAppCreationError(
                        f"{name}() needs a stream attribute argument")
                attrs.add(p.attribute)
                prefix.add(p.attribute)
                if name in ("stdDev", "stddev"):
                    sq.add(p.attribute)
                return
            raise SiddhiAppCreationError(
                f"unsupported window-condition function {name!r}; supported: "
                "count(), sum(x), avg(x), stdDev(x), eventTimestamp(first|"
                "last), first.x/last.x/bare attributes (min/max need full "
                "window scans and are not prefix-computable)")
        else:
            raise SiddhiAppCreationError(
                f"unsupported expression window term {type(e).__name__}")

    walk(expr)
    return _Terms(frozenset(attrs), frozenset(prefix), frozenset(sq))


def _compile_condition(expr: Expression):
    """Compile the AST into fn(env, s, q, cur, first_idx) -> bool scalar.

    env: {('seq', attr): f64[C+B], ('prefix', attr): f64[C+B+1],
          ('prefix_sq', attr): f64[C+B+1]} — arrival-order metric arrays.
    Window = [s, q]; `cur` indexes the current event (arrival q at the
    add-check, the just-popped event at pop-checks); first_idx = min(s, q)
    (the reference binds `first` to the popped event when the window
    empties)."""

    def build(e: Expression):
        if isinstance(e, And):
            l, r = build(e.left), build(e.right)
            return lambda *a: l(*a) & r(*a)
        if isinstance(e, Or):
            l, r = build(e.left), build(e.right)
            return lambda *a: l(*a) | r(*a)
        if isinstance(e, Not):
            f = build(e.expression)
            return lambda *a: ~f(*a)
        if isinstance(e, Compare):
            l, r = build(e.left), build(e.right)
            op = {
                CompareOp.LESS_THAN: lambda a, b: a < b,
                CompareOp.LESS_THAN_EQUAL: lambda a, b: a <= b,
                CompareOp.GREATER_THAN: lambda a, b: a > b,
                CompareOp.GREATER_THAN_EQUAL: lambda a, b: a >= b,
                CompareOp.EQUAL: lambda a, b: a == b,
                CompareOp.NOT_EQUAL: lambda a, b: a != b,
            }[e.op]
            return lambda *a: op(l(*a), r(*a))
        if isinstance(e, MathExpression):
            l, r = build(e.left), build(e.right)
            op = {
                MathOp.ADD: lambda a, b: a + b,
                MathOp.SUBTRACT: lambda a, b: a - b,
                MathOp.MULTIPLY: lambda a, b: a * b,
                MathOp.DIVIDE: lambda a, b: a / b,
                MathOp.MOD: lambda a, b: a % b,
            }[e.op]
            return lambda *a: op(l(*a), r(*a))
        if isinstance(e, Constant):
            v = bool(e.value) if e.type_name == "bool" else float(e.value)
            return lambda *a: v
        if isinstance(e, Variable):
            attr = e.attribute

            def var(env, s, q, cur, first_idx, _frame=e.stream_id, _a=attr):
                seq = env[("seq", _a)]
                idx = {"first": first_idx, "last": q}.get(_frame, cur)
                return seq[idx]

            return var
        if isinstance(e, AttributeFunction):
            name = e.name
            if name == "count":
                return lambda env, s, q, cur, fi: (
                    (q + 1 - s).astype(jnp.float64))
            if name == "eventTimestamp":
                frame = (e.parameters[0].attribute if e.parameters else
                         "current")

                def ets(env, s, q, cur, first_idx, _f=frame):
                    seq = env[("seq", TS_ATTR)]
                    idx = {"first": first_idx, "last": q}.get(_f, cur)
                    return seq[idx]

                return ets
            attr = e.parameters[0].attribute

            def agg(env, s, q, cur, fi, _n=name, _a=attr):
                pre = env[("prefix", _a)]
                total = pre[q + 1] - pre[s]
                if _n == "sum":
                    return total
                cnt = (q + 1 - s).astype(jnp.float64)
                mean = total / cnt
                if _n == "avg":
                    return mean
                sq = env[("prefix_sq", _a)]
                ex2 = (sq[q + 1] - sq[s]) / cnt
                return jnp.sqrt(jnp.maximum(ex2 - mean * mean, 0.0))

            return agg
        raise SiddhiAppCreationError(  # pragma: no cover — _collect guards
            f"unsupported expression term {type(e).__name__}")

    fn = build(expr)
    if isinstance(expr, (Constant, Variable, MathExpression,
                         AttributeFunction)):
        raise SiddhiAppCreationError(
            "expression window condition must be boolean")
    return fn


def _metric_env(terms: _Terms, ring_cols, ring_ts, comp_cols, comp_ts,
                base, winlen0, n_valid32, C: int, B: int) -> dict:
    """Arrival-order metric arrays: position r holds the event at overall
    index base + r (window rows [0, winlen0), this batch's arrivals at
    [winlen0, winlen0 + n_valid)); dead positions are 0."""
    env: dict = {}
    p = jnp.arange(B, dtype=jnp.int32)
    dest = jnp.where(p < n_valid32, winlen0 + p, C + B)
    base_mod = (base % C).astype(jnp.int32)
    live = jnp.arange(C, dtype=jnp.int32) < winlen0
    for attr in terms.attrs:
        ring_vals = ring_ts if attr == TS_ATTR else ring_cols[attr]
        comp_vals = comp_ts if attr == TS_ATTR else comp_cols[attr]
        arr = jax.lax.dynamic_slice(
            jnp.concatenate([ring_vals, ring_vals]), (base_mod,), (C,))
        arr = jnp.where(live, arr, jnp.zeros((), arr.dtype))
        seq = jnp.concatenate([arr, jnp.zeros((B,), arr.dtype)])
        seq = seq.at[dest].set(comp_vals.astype(arr.dtype), mode="drop")
        env[("seq", attr)] = seq.astype(jnp.float64)
    for attr in terms.prefix_attrs:
        seq = env[("seq", attr)]
        env[("prefix", attr)] = jnp.concatenate(
            [jnp.zeros((1,), jnp.float64), jnp.cumsum(seq)])
    for attr in terms.sq_attrs:
        seq = env[("seq", attr)]
        env[("prefix_sq", attr)] = jnp.concatenate(
            [jnp.zeros((1,), jnp.float64), jnp.cumsum(seq * seq)])
    return env


class GeneralExpressionWindow(ExpressionWindow):
    """Sliding expression window for ARBITRARY conditions: the reference's
    add-then-pop-while-false loop run exactly, as a device while_loop
    (sequential — each iteration advances the arrival or the pop cursor).
    Monotone conditions never get here (the factory prefers the vectorized
    ExpressionWindow)."""

    def __init__(self, layout: dict, batch_cap: int, condition: str):
        from ..compiler import parse_expression
        self.layout = layout
        self.B = batch_cap
        expr = parse_expression(condition)
        self.terms = _collect_terms(expr, layout)
        self.cond = _compile_condition(expr)
        self.conjuncts = []  # no static count bound
        self.C = max(dtypes.config.default_window_capacity, batch_cap)
        self.E = max(batch_cap, 1024)
        self.C = max(self.C, self.E)
        self.chunk_width = self.B + self.E
        self.W = _layout_words(layout)

    def _frontiers(self, ring_cols, ring_ts, comp_cols, comp_ts, expired,
                   winlen0, n_valid32, q):
        B, C, E = self.B, self.C, self.E
        env = _metric_env(self.terms, ring_cols, ring_ts, comp_cols,
                          comp_ts, expired, winlen0, n_valid32, C, B)
        cond = self.cond
        max_iter = jnp.int32(2 * B + E + 8)

        def check(s, qj, cur):
            fi = jnp.minimum(s, qj)
            return cond(env, s, qj, cur, fi)

        def cond_fn(carry):
            j, s, phase, s_vec, it = carry
            return (j < n_valid32) & (it < max_iter)

        def body_fn(carry):
            j, s, phase, s_vec, it = carry
            qj = winlen0 + j
            in_pop = phase == 1
            # add-check: window [s, qj], current = arrival qj
            # pop-check: pop event s (window becomes [s+1, qj]), current = s
            s_eval = jnp.where(in_pop, s + 1, s)
            cur = jnp.where(in_pop, s, qj)
            ok = check(s_eval, qj, cur)
            # pop loop stops on true, on empty window, or at the per-step
            # expiry-lane cap (deferred pops resume next step)
            stop = ok | (in_pop & ((s_eval > qj) | (s_eval >= jnp.int32(E))))
            advance = stop  # arrival j settles at s_eval
            s_new = jnp.where(in_pop, s_eval, s)
            s_settle = jnp.where(in_pop, s_eval, s)
            s_vec = jnp.where(
                advance, s_vec.at[j].set(s_settle), s_vec)
            j_new = jnp.where(advance, j + 1, j)
            phase_new = jnp.where(advance, jnp.int32(0), jnp.int32(1))
            return (j_new, s_new, phase_new, s_vec, it + 1)

        s_vec0 = jnp.zeros((B,), jnp.int32)
        j, s, phase, s_vec, _ = jax.lax.while_loop(
            cond_fn, body_fn,
            (jnp.int32(0), jnp.int32(0), jnp.int32(0), s_vec0, jnp.int32(0)))
        # lanes past n_valid (or past an iteration-cap bailout) take the
        # final frontier
        return jnp.where(jnp.arange(B, dtype=jnp.int32) < jnp.minimum(
            j, n_valid32), s_vec, s)


class GeneralBatchState(NamedTuple):
    ring: jax.Array  # [W, C] packed rows at overall index % C
    appended: jax.Array  # int64 total arrivals
    flushed: jax.Array  # int64 start of the accumulating window
    prev_start: jax.Array  # int64 start of the previous flushed batch
    overflow: jax.Array  # int64 rows lost to ring wrap / emission caps


class GeneralExpressionBatchWindow(WindowOp):
    """expressionBatch(condition[, includeTriggeringEvent]) for arbitrary
    conditions: greedy prefix segmentation (one condition check per arrival,
    a lax.scan), flushing [expired(prev flush), RESET, currents] like the
    other batch windows. count()-form conditions never get here (the
    factory lowers them to LengthBatchWindow)."""

    def __init__(self, layout: dict, batch_cap: int, condition: str,
                 include_trigger: bool = False):
        from ..compiler import parse_expression
        self.layout = layout
        self.B = batch_cap
        self.include_trigger = include_trigger
        expr = parse_expression(condition)
        self.terms = _collect_terms(expr, layout)
        self.cond = _compile_condition(expr)
        self.C = max(dtypes.config.default_window_capacity, 2 * batch_cap)
        self.E = max(batch_cap, 1024)
        self.P = self.E + self.B  # emission lanes per kind
        self.chunk_width = 2 * self.P + self.B
        self.W = _layout_words(layout)

    def init_state(self) -> GeneralBatchState:
        return GeneralBatchState(
            ring=jnp.zeros((self.W, self.C), jnp.uint32),
            appended=jnp.int64(0),
            flushed=jnp.int64(0),
            prev_start=jnp.int64(0),
            overflow=jnp.int64(0),
        )

    def step(self, state: GeneralBatchState, batch: EventBatch,
             now: jax.Array):
        B, C, P = self.B, self.C, self.P
        comp_mat, n_valid32 = compact_packed(batch, self.layout)
        n_valid = n_valid32.astype(jnp.int64)
        winlen0 = (state.appended - state.flushed).astype(jnp.int32)
        ring_cols, ring_ts = _unpack_rows(state.ring, self.layout)
        comp_cols, comp_ts = _unpack_rows(comp_mat, self.layout)
        env = _metric_env(self.terms, ring_cols, ring_ts, comp_cols,
                          comp_ts, state.flushed, winlen0, n_valid32, C, B)
        cond = self.cond
        inc = self.include_trigger

        def scan_body(s, j):
            qj = winlen0 + j
            valid_j = j < n_valid32
            fi = jnp.minimum(s, qj)
            ok = cond(env, s, qj, qj, fi)
            flush = valid_j & ~ok
            # a break on an EMPTY accumulating window flushes the arrival
            # itself immediately as [EXPIRED, CURRENT] and queues nothing
            # (ExpressionBatchWindowProcessor.java:336-343 else-branch)
            empty = flush & (s == qj)
            end_j = jnp.where(empty, qj + 1, qj + (1 if inc else 0))
            s_next = jnp.where(flush, end_j, s)
            return s_next, (flush, end_j, empty)

        s_final, (flush, end_j, empty_j) = jax.lax.scan(
            scan_body, jnp.int32(0), jnp.arange(B, dtype=jnp.int32))
        n_flushes = jnp.sum(flush, dtype=jnp.int32)
        k_j = jnp.cumsum(flush.astype(jnp.int32)) - 1  # flush index per lane
        BIG = jnp.int32(2 ** 30)
        scatter_to = jnp.where(flush, k_j, B)
        ends = jnp.full((B,), BIG, jnp.int32).at[scatter_to].set(
            end_j, mode="drop")
        trig = jnp.full((B,), B, jnp.int32).at[scatter_to].set(
            jnp.arange(B, dtype=jnp.int32), mode="drop")
        empty_k = jnp.zeros((B,), bool).at[scatter_to].set(
            empty_j, mode="drop")
        # flush k covers rel range [start_k, end_k); start_0 = 0 and
        # start_{k+1} = end_k (the trigger either joined flush k or starts
        # window k+1 — both give contiguous coverage)
        lim = jnp.where(n_flushes > 0,
                        ends[jnp.maximum(n_flushes - 1, 0)], 0)
        start_last = jnp.where(n_flushes >= 2,
                               ends[jnp.maximum(n_flushes - 2, 0)], 0)

        # --- CURRENT lanes: rel positions [0, lim) from `flushed` ---
        pe = jnp.arange(P, dtype=jnp.int32)
        cur_mat = _fetch_rel_packed(state.ring, comp_mat, state.flushed,
                                    state.appended, P)
        cur_k = searchsorted32(ends, pe, side="right")
        cur_valid = (pe < lim) & (cur_k < n_flushes)
        cur_trig = trig[jnp.clip(cur_k, 0, B - 1)]
        cur_hi = jnp.clip(cur_trig, 0, B) * 4 + KIND_CURRENT

        # --- EXPIRED lanes: previous flush re-emitted at this step's flush
        # k+1 (flush 0 expires the PREVIOUS step's last flushed batch);
        # empty-window flushes expire their own event at flush k itself and
        # leave nothing behind ---
        prev_len = (state.flushed - state.prev_start).astype(jnp.int32)
        exp_mat = _fetch_rel_packed(state.ring, comp_mat, state.prev_start,
                                    state.appended, P)
        r = pe - prev_len  # rel to `flushed` once past the prev batch
        in_prev = pe < prev_len
        own_k = searchsorted32(ends, jnp.maximum(r, 0), side="right")
        own_empty = empty_k[jnp.clip(own_k, 0, B - 1)]
        exp_k = jnp.where(in_prev, 0, jnp.where(own_empty, own_k, own_k + 1))
        # an event following an empty flush must not re-expire at the next
        # flush; an event of a normal flush expires at k+1 only if k+1 fires
        exp_valid = (exp_k < n_flushes) & (in_prev | (r < lim))
        exp_trig = trig[jnp.clip(exp_k, 0, B - 1)]
        exp_hi = jnp.clip(exp_trig, 0, B) * 4 + KIND_EXPIRED

        # --- RESET lanes: one per flush ---
        rj = jnp.arange(B, dtype=jnp.int32)
        rst_hi = jnp.clip(rj, 0, B) * 4 + KIND_RESET
        rst_mat = jnp.zeros((self.W, B), jnp.uint32)

        nowv = jnp.asarray(now, jnp.int64)
        all_hi = jnp.concatenate([exp_hi, rst_hi, cur_hi])
        all_lo = jnp.concatenate([pe, rj, pe])
        all_mat = jnp.concatenate([exp_mat, rst_mat, cur_mat], axis=1)
        all_emit = jnp.broadcast_to(nowv, (2 * P + B,))
        all_valid = jnp.concatenate([exp_valid, flush, cur_valid])
        all_types = jnp.concatenate([
            jnp.full((P,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.RESET, jnp.int8),
            jnp.full((P,), EventType.CURRENT, jnp.int8),
        ])
        chunk = _sort_chunk_packed(all_hi, all_lo, all_mat, all_emit,
                                   all_valid, all_types, self.layout,
                                   self.chunk_width)

        new_ring = _append_packed(state.ring, comp_mat, state.appended,
                                  n_valid32)
        appended1 = state.appended + n_valid
        flushed1 = state.flushed + s_final.astype(jnp.int64)
        empty_last = empty_k[jnp.clip(n_flushes - 1, 0, B - 1)]
        prev_start1 = jnp.where(
            n_flushes > 0,
            state.flushed + jnp.where(empty_last, lim,
                                      start_last).astype(jnp.int64),
            state.prev_start)
        # monitored losses: ring wrap past prev_start + flushes wider than
        # the emission block
        span0 = jnp.maximum(state.appended - state.prev_start - C, 0)
        span1 = jnp.maximum(appended1 - prev_start1 - C, 0)
        dropped_emit = jnp.maximum(lim - P, 0).astype(jnp.int64)
        new_state = GeneralBatchState(
            ring=new_ring,
            appended=appended1,
            flushed=flushed1,
            prev_start=prev_start1,
            overflow=(state.overflow + jnp.maximum(span1 - span0, 0)
                      + dropped_emit),
        )
        return new_state, chunk

    def contents(self, state: GeneralBatchState, now: jax.Array):
        """Joins see the accumulating (unflushed) window."""
        ring_cols, ring_ts = _unpack_rows(state.ring, self.layout)
        live = _ring_live_mask(self.C, state.flushed, state.appended)
        return ring_cols, ring_ts, live
