"""Additional window operators: cron, hopping, frequent, lossyFrequent.

Reference: core/query/processor/stream/window/ —
CronWindowProcessor.java (quartz-driven tumble), HoppingWindowProcessor.java
(emit every hop covering the last windowTime), FrequentWindowProcessor.java
(Misra-Gries counter map, evicted keys emit EXPIRED),
LossyFrequentWindowProcessor.java (lossy counting with support/error bounds).

Batched divergences (documented per class): counter updates happen at
micro-batch granularity instead of per event, and multiple simultaneous
boundary crossings collapse into the latest one.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from .search import searchsorted32
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError
from .windows import (
    BIG,
    WindowOp,
    _empty_like_cols,
    _gather_overall,
    _ring_live_mask,
    _scatter_append,
    compact,
)


class CronState(NamedTuple):
    ring_cols: dict
    ring_ts: jax.Array
    appended: jax.Array  # int64 total arrivals
    flushed: jax.Array  # int64 arrivals already emitted
    prev_start: jax.Array  # int64 start of the previous flush
    next_fire: jax.Array  # int64 epoch ms of the next cron fire


class CronWindow(WindowOp):
    """cron('0 0/5 * * * ?'): tumble on cron fire times. The next-fire instant
    lives IN the state; crossing it flushes the buffer. The cron expression is
    evaluated host-side through jax.pure_callback — one scalar callback per
    fire, zero per quiet step (reference: CronWindowProcessor.java delegates
    to quartz the same way)."""

    needs_heartbeat = True

    def __init__(self, layout: dict, batch_cap: int, expr: str,
                 expired_on: bool = True):
        from ..core.trigger import CronSchedule
        self.layout = layout
        self.B = batch_cap
        self.expired_on = expired_on
        self.schedule = CronSchedule(expr)
        self.C = max(dtypes.config.default_window_capacity, 4 * batch_cap)
        self.chunk_width = 2 * self.C + 1

    def init_state(self) -> CronState:
        return CronState(
            ring_cols=_empty_like_cols(self.layout, self.C),
            ring_ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            appended=jnp.int64(0),
            flushed=jnp.int64(0),
            prev_start=jnp.int64(0),
            next_fire=jnp.int64(-1),  # -1 = not yet scheduled
        )

    def _host_next_fire(self, after_ms):
        def fn(t):
            import numpy as np
            nxt = self.schedule.next_fire_ms(int(t))
            return np.int64(nxt if nxt is not None else 2**62)

        return jax.pure_callback(
            fn, jax.ShapeDtypeStruct((), jnp.int64), after_ms)

    def step(self, state: CronState, batch: EventBatch, now: jax.Array):
        C = self.C
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        appended1 = state.appended + n_valid
        ring_cols, ring_ts = _scatter_append(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, n_valid)

        # lazy initial schedule: from the earliest unprocessed event (so a
        # boundary between that event and the first watermark still fires)
        idx_b = jnp.arange(self.B, dtype=jnp.int64)
        min_ts = jnp.min(jnp.where(idx_b < n_valid, comp_ts, BIG))
        base = jnp.where(n_valid > 0, jnp.minimum(min_ts, now), now)
        # lax.cond so the host callback runs only when actually unscheduled
        next_fire = jax.lax.cond(
            state.next_fire < 0,
            lambda: self._host_next_fire(base - 1),
            lambda: state.next_fire)
        fire = next_fire <= now

        # currents: overall [flushed, appended1); expired: [prev_start, flushed)
        o = jnp.arange(C, dtype=jnp.int64)
        o_cur = state.flushed + o
        # ring guard: only the most recent C arrivals survive between fires
        # (same truncation rule as _scatter_append); older slots were
        # overwritten and must not emit stale duplicates
        cur_valid = fire & (o_cur < appended1) & (appended1 - o_cur <= C)
        cur_cols, cur_ts = _gather_overall(
            ring_cols, ring_ts, comp_cols, comp_ts, appended1, o_cur)
        o_exp = state.prev_start + o
        exp_valid = (fire & self.expired_on & (o_exp < state.flushed)
                     & (state.flushed - o_exp <= C))
        exp_cols, exp_ts = _gather_overall(
            ring_cols, ring_ts, comp_cols, comp_ts, appended1, o_exp)

        cols = {k: jnp.concatenate(
            [exp_cols[k], jnp.zeros((1,), v.dtype), cur_cols[k]])
            for k, v in ring_cols.items()}
        ts = jnp.concatenate([exp_ts, now[None], cur_ts])
        valid = jnp.concatenate(
            [exp_valid, fire[None] & (state.flushed > state.prev_start), cur_valid])
        types = jnp.concatenate([
            jnp.full((C,), EventType.EXPIRED, jnp.int8),
            jnp.full((1,), EventType.RESET, jnp.int8),
            jnp.full((C,), EventType.CURRENT, jnp.int8)])
        chunk = EventBatch(ts=ts, cols=cols, valid=valid, types=types)

        new_next = jax.lax.cond(
            fire, lambda: self._host_next_fire(now), lambda: next_fire)
        new_state = CronState(
            ring_cols=ring_cols, ring_ts=ring_ts,
            appended=appended1,
            flushed=jnp.where(fire, appended1, state.flushed),
            prev_start=jnp.where(fire, state.flushed, state.prev_start),
            next_fire=new_next,
        )
        return new_state, chunk

    def contents(self, state: CronState, now: jax.Array):
        live = _ring_live_mask(self.C, state.flushed, state.appended)
        return state.ring_cols, state.ring_ts, live


class HopState(NamedTuple):
    ring_cols: dict
    ring_ts: jax.Array
    appended: jax.Array  # int64 total arrivals
    last_hop: jax.Array  # int64 index of the last emitted hop boundary


class HoppingWindow(WindowOp):
    """hopping(windowTime, hopTime): every hopTime emit the events of the last
    windowTime (overlapping when window > hop; reference:
    HoppingWindowProcessor.java). Batched divergence: multiple hop boundaries
    crossed inside one micro-batch collapse into the latest boundary's
    emission."""

    needs_heartbeat = True

    def __init__(self, layout: dict, batch_cap: int, window_ms: int,
                 hop_ms: int):
        if hop_ms <= 0 or window_ms <= 0:
            raise SiddhiAppCreationError("hopping needs positive window and hop")
        self.layout = layout
        self.B = batch_cap
        self.W = window_ms
        self.H = hop_ms
        self.C = max(dtypes.config.default_window_capacity, 2 * batch_cap)
        self.chunk_width = self.C + 1  # RESET + window contents

    def init_state(self) -> HopState:
        return HopState(
            ring_cols=_empty_like_cols(self.layout, self.C),
            ring_ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            appended=jnp.int64(0),
            last_hop=jnp.int64(0),
        )

    def step(self, state: HopState, batch: EventBatch, now: jax.Array):
        C = self.C
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        appended1 = state.appended + n_valid
        ring_cols, ring_ts = _scatter_append(
            state.ring_cols, state.ring_ts, comp_cols, comp_ts,
            state.appended, n_valid)

        hop_idx = now // jnp.int64(self.H)
        fire = hop_idx > state.last_hop
        boundary = hop_idx * jnp.int64(self.H)

        live = _ring_live_mask(C, jnp.maximum(appended1 - C, 0), appended1)
        in_window = live & (ring_ts > boundary - jnp.int64(self.W)) \
            & (ring_ts <= boundary)
        valid = jnp.concatenate([fire[None], fire & in_window])
        cols = {k: jnp.concatenate([jnp.zeros((1,), v.dtype), v])
                for k, v in ring_cols.items()}
        ts = jnp.concatenate([now[None], ring_ts])
        types = jnp.concatenate([
            jnp.full((1,), EventType.RESET, jnp.int8),
            jnp.full((C,), EventType.CURRENT, jnp.int8)])
        chunk = EventBatch(ts=ts, cols=cols, valid=valid, types=types)

        new_state = HopState(
            ring_cols=ring_cols, ring_ts=ring_ts, appended=appended1,
            last_hop=jnp.where(fire, hop_idx, state.last_hop))
        return new_state, chunk

    def contents(self, state: HopState, now: jax.Array):
        # probe the same (boundary - W, boundary] interval step() last
        # emitted, so joins/pull queries see exactly the emitted hop — not
        # events newer than the last boundary
        boundary = state.last_hop * jnp.int64(self.H)
        live = _ring_live_mask(self.C, jnp.maximum(state.appended - self.C, 0),
                               state.appended)
        in_window = live & (state.ring_ts > boundary - jnp.int64(self.W)) \
            & (state.ring_ts <= boundary)
        return state.ring_cols, state.ring_ts, in_window


class FrequentState(NamedTuple):
    slot_keys: jax.Array  # int64[N], PAD when empty
    slot_counts: jax.Array  # int64[N]
    slot_cols: dict  # latest event per slot
    slot_ts: jax.Array  # int64[N]
    total: jax.Array  # int64 total arrivals (lossyFrequent)


_PAD = jnp.iinfo(jnp.int64).max


class FrequentWindow(WindowOp):
    """frequent(N[, attrs...]): keep events whose attribute combination is one
    of the N most frequent — Misra-Gries counters (reference:
    FrequentWindowProcessor.java). Evicted keys emit their remembered latest
    event as EXPIRED. Batched divergence: counter decrements are applied per
    micro-batch, so within-batch admit/evict interleavings collapse."""

    def __init__(self, layout: dict, batch_cap: int, n_slots: int,
                 key_attrs: Optional[list] = None, support: float = 0.0,
                 error: float = 0.0, lossy: bool = False):
        self.layout = layout
        self.B = batch_cap
        self.N = n_slots
        self.key_attrs = key_attrs or list(layout.keys())
        for a in self.key_attrs:
            if a not in layout:
                raise SiddhiAppCreationError(f"frequent: no attribute {a!r}")
        self.support = support
        self.error = error
        self.lossy = lossy
        self.chunk_width = batch_cap + n_slots  # currents + evict-expireds

    def init_state(self) -> FrequentState:
        N = self.N
        return FrequentState(
            slot_keys=jnp.full((N,), _PAD, jnp.int64),
            slot_counts=jnp.zeros((N,), jnp.int64),
            slot_cols=_empty_like_cols(self.layout, N),
            slot_ts=jnp.zeros((N,), dtypes.TS_DTYPE),
            total=jnp.int64(0),
        )

    _SCALE = 1_000_000  # fixed-point for support/error thresholds

    def step(self, state: FrequentState, batch: EventBatch, now: jax.Array):
        from .groupby import hash_columns
        N, B = self.N, self.B
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        lane_live = jnp.arange(B) < n_valid
        keys = hash_columns([comp_cols[a] for a in self.key_attrs])
        keys = jnp.where(keys == _PAD, _PAD - 1, keys)

        # batch-unique keys (as runs of the sorted key array) with counts
        sk = jnp.where(lane_live, keys, _PAD)
        order = jnp.argsort(sk, stable=True)
        s = sk[order]
        first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
        uniq_rank = jnp.cumsum(first.astype(jnp.int32)) - 1  # run id per lane
        run_count = jax.ops.segment_sum(
            (s != _PAD).astype(jnp.int64), uniq_rank, num_segments=B)
        idx = jnp.arange(B)
        run_first = jax.ops.segment_min(
            jnp.where(s != _PAD, idx, B - 1), uniq_rank, num_segments=B)
        run_key = jnp.where(run_count > 0, s[jnp.clip(run_first, 0, B - 1)], _PAD)
        uniq_live = run_count > 0

        # match batch-unique keys against tracked slots
        slot_of = _match(state.slot_keys, run_key)  # [B] slot idx or N
        tracked = slot_of < N

        # 1) tracked keys: counts += batch count
        counts1 = state.slot_counts.at[
            jnp.where(tracked & uniq_live, slot_of, N)].add(
            run_count, mode="drop")

        # 2) untracked keys fill free slots (j-th new key → j-th free slot)
        free = state.slot_keys == _PAD
        sorted_free = jnp.sort(jnp.where(free, jnp.arange(N), N))  # [N]
        new_need = uniq_live & ~tracked
        new_rank = jnp.cumsum(new_need.astype(jnp.int32)) - 1
        n_free = jnp.sum(free.astype(jnp.int32))
        placed = new_need & (new_rank < n_free)
        place_slot = jnp.where(
            placed, sorted_free[jnp.clip(new_rank, 0, N - 1)], N)
        keys1 = state.slot_keys.at[place_slot].set(run_key, mode="drop")
        counts2 = counts1.at[place_slot].set(run_count, mode="drop")

        # 3) Misra-Gries decrement: arrivals that found no slot decrement all
        unplaced_arrivals = jnp.sum(jnp.where(new_need & ~placed, run_count, 0))
        occupied = keys1 != _PAD
        counts3 = jnp.where(occupied,
                            jnp.maximum(counts2 - unplaced_arrivals, 0), 0)
        evicted = occupied & (counts3 == 0)
        keys2 = jnp.where(evicted, _PAD, keys1)

        total1 = state.total + n_valid
        if self.lossy:
            # lossy-counting prune: drop keys below the error floor
            # (reference: LossyFrequentWindowProcessor). Fixed-point int math.
            err = jnp.int64(int(self.error * self._SCALE))
            lossy_evict = (keys2 != _PAD) & (
                counts3 * self._SCALE < err * total1)
            evicted = evicted | lossy_evict
            keys2 = jnp.where(lossy_evict, _PAD, keys2)

        # remembered latest event per tracked slot — last lane per slot via a
        # commutative scatter-max (duplicate-index .set order is undefined)
        lane_slot_of = _match(keys2, keys)  # per original lane
        lane_tracked = lane_live & (lane_slot_of < N)
        scat_slot = jnp.where(lane_tracked, lane_slot_of, N)
        last_lane = jnp.full((N + 1,), -1, jnp.int32).at[scat_slot].max(
            idx.astype(jnp.int32), mode="drop")[:N]
        has_new = last_lane >= 0
        g = jnp.clip(last_lane, 0, B - 1)
        cols1 = {k: jnp.where(has_new, comp_cols[k][g], state.slot_cols[k])
                 for k in self.layout}
        ts1 = jnp.where(has_new, comp_ts[g], state.slot_ts)

        # chunk: CURRENT lanes whose key is tracked post-update (lossy adds a
        # support threshold), EXPIRED = evicted slots' remembered events.
        # Only slots occupied BEFORE this batch may emit expired — a key
        # admitted and decremented away within one batch has no remembered
        # event (its slot_cols still hold the previous occupant / zeros)
        evicted_emit = evicted & (state.slot_keys != _PAD)
        cur_valid = lane_tracked
        if self.lossy:
            thr = jnp.int64(int((self.support - self.error) * self._SCALE))
            lane_count = counts3[jnp.clip(lane_slot_of, 0, N - 1)]
            cur_valid = cur_valid & (lane_count * self._SCALE >= thr * total1)
        ev_cols = {k: jnp.concatenate([comp_cols[k], state.slot_cols[k]])
                   for k in self.layout}
        ev_ts = jnp.concatenate([comp_ts, state.slot_ts])
        chunk = EventBatch(
            ts=ev_ts, cols=ev_cols,
            valid=jnp.concatenate([cur_valid, evicted_emit]),
            types=jnp.concatenate([
                jnp.full((B,), EventType.CURRENT, jnp.int8),
                jnp.full((N,), EventType.EXPIRED, jnp.int8)]))

        new_state = FrequentState(
            slot_keys=keys2, slot_counts=counts3, slot_cols=cols1,
            slot_ts=ts1, total=total1)
        return new_state, chunk

    def contents(self, state: FrequentState, now: jax.Array):
        return state.slot_cols, state.slot_ts, state.slot_keys != _PAD


def _match(table_keys: jax.Array, query_keys: jax.Array) -> jax.Array:
    """Index of each query key in table_keys, or len(table) when absent."""
    N = table_keys.shape[0]
    order = jnp.argsort(table_keys, stable=True)
    sorted_keys = table_keys[order]
    pos = searchsorted32(sorted_keys, query_keys)
    pos_c = jnp.clip(pos, 0, N - 1)
    found = sorted_keys[pos_c] == query_keys
    return jnp.where(found, order[pos_c], N).astype(jnp.int32)


# --------------------------------------------------------------------------- #
# keyed session window
# --------------------------------------------------------------------------- #


class KeyedSessionState(NamedTuple):
    ring_cols: dict
    ring_ts: jax.Array  # int64[C]
    ring_key: jax.Array  # int32[C] key slot per row
    ring_sess: jax.Array  # int32[C] session id per row (per key)
    ring_emitted: jax.Array  # bool[C] expired emission already happened
    appended: jax.Array  # int64 total arrivals
    last_ts: jax.Array  # int64[K] newest event ts per key
    sess: jax.Array  # int32[K] current open session id per key
    has: jax.Array  # bool[K] key has an open session
    dropped: jax.Array  # int64 lifetime events dropped (key >= capacity)


class KeyedSessionWindow(WindowOp):
    """session(gap, key): one independent session per key value (reference:
    SessionWindowProcessor with a session-key parameter keeps a per-key
    session map). Device design: key slots are the key attribute's
    dictionary codes (string keys — dense by construction); per-key
    last-ts/session tables replace the scalar session state, ring rows carry
    (key, session, emitted) tags, and a session closing (in-batch gap or
    watermark) expires exactly its rows via a masked ring scan.

    Documented divergences: expired lanes of sessions closed within a batch
    emit BEFORE that batch's CURRENT lanes (the reference interleaves per
    triggering event); key codes beyond the slot capacity
    (config.session_key_capacity) have their events dropped from the window
    — size the capacity to the key domain."""

    needs_heartbeat = True

    def __init__(self, layout: dict, batch_cap: int, gap_ms: int,
                 key_attr: str, capacity: Optional[int] = None):
        if gap_ms <= 0:
            raise SiddhiAppCreationError("session gap must be positive")
        if key_attr not in layout:
            raise SiddhiAppCreationError(
                f"session key {key_attr!r} is not a stream attribute")
        attr_types = getattr(layout, "attr_types", None)
        if attr_types is None:
            raise SiddhiAppCreationError(
                "keyed sessions need attribute type information "
                "(ops/windows.py make_layout) to validate the key attribute")
        from ..query_api.definition import AttributeType
        if attr_types.get(key_attr) not in (AttributeType.STRING,
                                            AttributeType.INT,
                                            AttributeType.LONG):
            raise SiddhiAppCreationError(
                "session keys must be string (dictionary codes) or "
                "small non-negative int attributes")
        self.layout = dict(layout)
        self.B = batch_cap
        self.gap = gap_ms
        self.key_attr = key_attr
        self.K = dtypes.config.session_key_capacity
        self.C = capacity or max(dtypes.config.default_window_capacity // 4,
                                 2 * batch_cap)
        # emission block cannot exceed the ring (slicing would misalign the
        # fixed-width chunk concatenation)
        self.E = min(max(batch_cap, 1024), self.C)
        self.chunk_width = self.B + self.E

    def init_state(self) -> KeyedSessionState:
        C, K = self.C, self.K
        return KeyedSessionState(
            ring_cols=_empty_like_cols(self.layout, C),
            ring_ts=jnp.zeros((C,), dtypes.TS_DTYPE),
            ring_key=jnp.zeros((C,), jnp.int32),
            ring_sess=jnp.zeros((C,), jnp.int32),
            ring_emitted=jnp.ones((C,), bool),  # empty slots count as done
            appended=jnp.int64(0),
            last_ts=jnp.zeros((K,), dtypes.TS_DTYPE),
            sess=jnp.zeros((K,), jnp.int32),
            has=jnp.zeros((K,), bool),
            dropped=jnp.int64(0),
        )

    def step(self, state: KeyedSessionState, batch: EventBatch,
             now: jax.Array):
        from ..core.event import EventType
        from .windows import compact

        B, C, E, K = self.B, self.C, self.E, self.K
        gap = jnp.int64(self.gap)
        comp_cols, comp_ts, n_valid, _ = compact(batch)
        p32 = jnp.arange(B, dtype=jnp.int32)
        is_arr = p32 < n_valid
        key = comp_cols[self.key_attr].astype(jnp.int32)
        ok = is_arr & (key >= 0) & (key < K)
        key_c = jnp.clip(key, 0, K - 1)

        # --- per-arrival session ids: group arrivals by key (stable sort
        # keeps arrival order inside each key run) ---
        skey = jnp.where(ok, key_c, jnp.int32(K))
        order = jnp.argsort(skey, stable=True)
        o_key = skey[order]
        o_ts = comp_ts[order]
        o_ok = ok[order]
        seg_start = jnp.concatenate(
            [jnp.ones((1,), bool), o_key[1:] != o_key[:-1]])
        prev_ts = jnp.concatenate([jnp.zeros((1,), o_ts.dtype), o_ts[:-1]])
        base_last = state.last_ts[jnp.clip(o_key, 0, K - 1)]
        base_has = state.has[jnp.clip(o_key, 0, K - 1)]
        # break before this arrival: vs the key's stored last ts at segment
        # start, vs the in-batch predecessor inside a segment
        brk = jnp.where(seg_start,
                        base_has & (o_ts - base_last > gap),
                        o_ts - prev_ts > gap) & o_ok
        # per-key cumulative breaks (segmented cumsum)
        from .groupby import _segmented_scan
        incr = _segmented_scan(brk.astype(jnp.int32), seg_start,
                               jnp.add, jnp.int32(0))
        base_sess = state.sess[jnp.clip(o_key, 0, K - 1)]
        o_sess = base_sess + incr
        # back to arrival order
        arr_sess = jnp.zeros((B,), jnp.int32).at[order].set(o_sess)

        # --- per-key tables after this batch ---
        seg_end = jnp.concatenate([seg_start[1:], jnp.ones((1,), bool)])
        wkey = jnp.where(o_ok & seg_end, o_key, K)
        new_last = state.last_ts.at[wkey].set(o_ts, mode="drop")
        new_sess = state.sess.at[wkey].set(o_sess, mode="drop")
        new_has = state.has.at[wkey].set(True, mode="drop")

        # watermark closure: keys whose open session has gone quiet bump
        # their session id (their rows become expired below) and reset
        wm_close = new_has & (now - new_last > gap)
        new_sess = jnp.where(wm_close, new_sess + 1, new_sess)
        new_has = new_has & ~wm_close

        # --- ring append (arrivals with their session tags): PACK ok lanes
        # so dropped-key arrivals leave no holes (appended advances by
        # sum(ok); a positional write would misalign every later lane) ---
        rank = jnp.cumsum(ok.astype(jnp.int32)) - 1
        slot = jnp.where(ok, ((state.appended % C).astype(jnp.int32) + rank) % C,
                         C)
        ring_cols = {k: state.ring_cols[k].at[slot].set(comp_cols[k],
                                                        mode="drop")
                     for k in self.layout}
        ring_ts = state.ring_ts.at[slot].set(comp_ts, mode="drop")
        ring_key = state.ring_key.at[slot].set(key_c, mode="drop")
        ring_sess = state.ring_sess.at[slot].set(arr_sess, mode="drop")
        ring_emitted = state.ring_emitted.at[slot].set(False, mode="drop")
        appended1 = state.appended + jnp.sum(ok, dtype=jnp.int32).astype(
            jnp.int64)

        # --- expired: un-emitted rows whose session is no longer open ---
        live = _ring_live_mask(C, jnp.maximum(appended1 - C, 0), appended1)
        open_sess = new_sess[ring_key]
        closed = live & ~ring_emitted & (ring_sess < open_sess)
        # top-E selection in ARRIVAL order (ring slots rotate once the ring
        # wraps; expired lanes must emit oldest-first). Sessions close
        # rarely; E bounds the per-step emission — the rest emit next step.
        base1 = (appended1 % C).astype(jnp.int32)
        rel_age = (jnp.arange(C, dtype=jnp.int32) - base1) % C
        ekey = jnp.where(closed, rel_age, jnp.int32(C))
        eorder = jnp.argsort(ekey, stable=True)[:E]
        esel = closed[eorder]
        emitted2 = ring_emitted | (jnp.zeros((C,), bool).at[
            jnp.where(esel, eorder, C)].set(True, mode="drop"))

        exp_cols = {k: ring_cols[k][eorder] for k in self.layout}
        exp_ts = ring_ts[eorder]

        all_cols = {k: jnp.concatenate([exp_cols[k], comp_cols[k]])
                    for k in self.layout}
        all_ts = jnp.concatenate([exp_ts, comp_ts])
        all_valid = jnp.concatenate([esel, ok])
        all_types = jnp.concatenate([
            jnp.full((E,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.CURRENT, jnp.int8),
        ])
        chunk = EventBatch(ts=all_ts, cols=all_cols, valid=all_valid,
                           types=all_types)

        new_state = KeyedSessionState(
            ring_cols=ring_cols, ring_ts=ring_ts, ring_key=ring_key,
            ring_sess=ring_sess, ring_emitted=emitted2,
            appended=appended1, last_ts=new_last, sess=new_sess,
            has=new_has,
            dropped=state.dropped + jnp.sum(is_arr & ~ok, dtype=jnp.int64))
        return new_state, chunk

    def contents(self, state: KeyedSessionState, now: jax.Array):
        live = _ring_live_mask(self.C, jnp.maximum(state.appended - self.C, 0),
                               state.appended)
        open_rows = live & ~state.ring_emitted & (
            state.ring_sess >= state.sess[state.ring_key])
        return state.ring_cols, state.ring_ts, open_rows
