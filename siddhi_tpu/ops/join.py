"""Device join kernels (reference: core/query/input/stream/join/JoinProcessor.java:45).

The reference walks each arriving event through `find()` on the opposite
window/table with a CompiledCondition (per-event linked-list probe, optionally
index-accelerated by the table's CollectionExecutors). The TPU redesign probes
a whole micro-batch at once with two strategies chosen at plan time:

- **equi join** (the common case; BASELINE config 5): equality conjuncts
  `A.x == B.y` are extracted from the ON condition; build-side rows are
  key-hash sorted per probe and candidates located by `searchsorted`, bounded
  to K candidates per probe lane. Hashes only generate candidates — the exact
  ON condition re-verifies every pair, so hash collisions cannot produce false
  matches. This is a sort-merge join: one sort of the build ring + one
  binary-search per probe lane, all inside the query's fused XLA program.
- **cross join** fallback for ON conditions with no equality conjunct: a
  [B, C] mask with per-row top-K selection. Requires a small build side.

Both produce a fixed-width pair block: [B*K] matched lanes (+[B] outer lanes
for left/right/full outer), each pair carrying both frames' columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .search import (
    searchsorted32,
    stable_argsort_bounded,
    stable_partition_order,
)

from ..core import dtypes
from ..errors import SiddhiAppCreationError
from ..query_api.definition import AttributeType
from ..query_api.expression import And, Compare, CompareOp, Expression, Variable
from .expr_compile import CompiledExpr, Scope, TypeResolver, compile_expression
from .groupby import hash_columns32

BIGKEY = np.uint32(0xFFFFFFFF)  # numpy literal — see ops/windows.py BIG note


def split_conjuncts(expr: Optional[Expression]) -> list[Expression]:
    if expr is None:
        return []
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def collect_vars(expr):
    """All Variable leaves of a condition AST — ONE walker shared by the
    join planner and the condition-based store fallback."""
    out = []

    def walk(e):
        if isinstance(e, Variable):
            out.append(e)
            return
        for a in ("left", "right", "expression"):
            sub = getattr(e, a, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(e, "parameters", ()) or ():
            if isinstance(p, Expression):
                walk(p)

    if expr is not None:
        walk(expr)
    return out


def frames_of(expr: Expression, resolver: TypeResolver) -> set:
    """Frame refs referenced by an expression (resolving unqualified vars)."""
    out: set = set()

    def walk(e):
        if isinstance(e, Variable):
            ref, _, _ = resolver.resolve(e)
            out.add(ref if ref is not None else resolver.default_frame)
            return
        for attr in ("left", "right", "expression"):
            sub = getattr(e, attr, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(e, "parameters", ()) or ():
            if isinstance(p, Expression):
                walk(p)

    walk(expr)
    return out


@dataclass
class JoinPlan:
    """Extracted equi-keys + residual condition for one (probe, build) pair."""

    probe_keys: list  # CompiledExpr evaluated on the probe frame
    build_keys: list  # CompiledExpr evaluated on the build frame
    residual: Optional[CompiledExpr]  # full ON condition (pair-verified)


def plan_join(on: Optional[Expression], probe_frame: str, build_frame: str,
              resolver: TypeResolver, registry) -> JoinPlan:
    probe_keys: list = []
    build_keys: list = []
    for conj in split_conjuncts(on):
        if isinstance(conj, Compare) and conj.op == CompareOp.EQUAL:
            lf = frames_of(conj.left, resolver)
            rf = frames_of(conj.right, resolver)
            if lf <= {probe_frame} and rf <= {build_frame}:
                probe_keys.append(compile_expression(conj.left, resolver, registry))
                build_keys.append(compile_expression(conj.right, resolver, registry))
                continue
            if lf <= {build_frame} and rf <= {probe_frame}:
                probe_keys.append(compile_expression(conj.right, resolver, registry))
                build_keys.append(compile_expression(conj.left, resolver, registry))
                continue
    residual = compile_expression(on, resolver, registry) if on is not None else None
    if residual is not None and residual.type != AttributeType.BOOL:
        raise SiddhiAppCreationError("join ON condition must be boolean")
    return JoinPlan(probe_keys, build_keys, residual)


def _hash_exprs(keys: Sequence[CompiledExpr], scope: Scope) -> jax.Array:
    # avoid colliding with the BIGKEY invalid sentinel
    h = hash_columns32([k(scope) for k in keys])
    return jnp.where(h == BIGKEY, jnp.uint32(0xFFFFFFFE), h)


def probe_equi(plan: JoinPlan, probe_scope: Scope, probe_valid: jax.Array,
               build_cols: dict, build_ts: jax.Array, build_valid: jax.Array,
               build_frame: str, k_max: int):
    """Candidate pairs via sort-merge on key hashes.

    Returns (probe_lane[P], build_row[P], pair_valid[P]) with P = B*k_max.
    """
    B = probe_valid.shape[0]
    C = build_ts.shape[0]

    bscope = Scope()
    bscope.add_frame(build_frame, build_cols, build_ts, build_valid, default=True)
    bkeys = jnp.where(build_valid, _hash_exprs(plan.build_keys, bscope), BIGKEY)
    pkeys = _hash_exprs(plan.probe_keys, probe_scope)

    order = jnp.argsort(bkeys, stable=True)  # invalid rows sort last
    sorted_keys = bkeys[order]
    start = searchsorted32(sorted_keys, pkeys, side="left")

    k = jnp.arange(k_max)
    pos = start[:, None] + k[None, :]  # [B,K]
    pos_c = jnp.clip(pos, 0, C - 1)
    cand_valid = (pos < C) & (sorted_keys[pos_c] == pkeys[:, None]) & \
        probe_valid[:, None]
    build_row = order[pos_c]  # [B,K]

    probe_lane = jnp.broadcast_to(jnp.arange(B)[:, None], (B, k_max)).reshape(-1)
    return probe_lane, build_row.reshape(-1), cand_valid.reshape(-1)


def compact_pairs(probe_lane: jax.Array, build_row: jax.Array,
                  pair_valid: jax.Array, pair_cap: int):
    """Compact the sparse [B*k_max] candidate block to `pair_cap` lanes.

    Matches are typically ~1 per probe event, so downstream frame gathers,
    residual verification, and the selector would otherwise run at k_max x
    the real pair count. One cumsum + one 2-word row scatter; candidate
    order (probe-lane major) is preserved, keeping emission order intact.
    Pairs beyond pair_cap are dropped (bounded fan-out, like k_max — size
    via dtypes.config.join_pair_cap_factor)."""
    pos = jnp.cumsum(pair_valid.astype(jnp.int32)) - 1
    dest = jnp.where(pair_valid & (pos < pair_cap), pos, pair_cap)
    packed = jnp.stack([probe_lane.astype(jnp.int32),
                        build_row.astype(jnp.int32)], axis=1)
    rows = jnp.zeros((pair_cap, 2), jnp.int32).at[dest].set(
        packed, mode="drop")
    n = jnp.minimum(jnp.sum(pair_valid, dtype=jnp.int32), pair_cap)
    pv = jnp.arange(pair_cap, dtype=jnp.int32) < n
    return rows[:, 0], rows[:, 1], pv


class MultimapState(NamedTuple):
    """Incrementally maintained hash multimap over a FIFO window ring.

    Replaces the per-step build-side sort of `probe_equi` for sliding-window
    build sides (the reference's per-event `find()` against the opposite
    window, JoinProcessor.java:140-143): entries are inserted as rows append
    to the ring and never explicitly deleted — FIFO overwrite invalidates
    them, and chains through an overwritten slot terminate safely because
    every entry past it is older and therefore also overwritten.

    Everything is i32/u32 — int64 lane math is software-emulated on TPU and
    dominated the first cut of this structure. Entries are addressed by RING
    POSITION; liveness rides a u32 arrival-index tag per slot compared by
    wraparound age (`appended - tag`), exact while the window length stays
    under 2^32 (a slot idle for exactly ~2^32 arrivals could alias — every
    slot is rewritten each C arrivals, so this needs a 4-billion-event gap).
    """

    heads: jax.Array  # i32[H] ring position of the newest entry per bucket
    nexts: jax.Array  # i32[C] ring position of the next-older chain entry
    slot_hash: jax.Array  # u32[C] full 32-bit key hash of the slot's row
    slot_seq: jax.Array  # u32[C] arrival index (mod 2^32) of the slot's row


def multimap_init(ring_capacity: int, n_buckets: int) -> MultimapState:
    return MultimapState(
        heads=jnp.full((n_buckets,), -1, jnp.int32),
        nexts=jnp.full((ring_capacity,), -1, jnp.int32),
        slot_hash=jnp.zeros((ring_capacity,), jnp.uint32),
        slot_seq=jnp.full((ring_capacity,), 0xFFFFFFFF, jnp.uint32),
    )


def multimap_buckets(ring_capacity: int) -> int:
    """Power-of-two bucket count ~2x the ring: short chains, cheap masking."""
    h = 1
    while h < 2 * ring_capacity:
        h *= 2
    return h


def multimap_append(mm: MultimapState, hashes: jax.Array, live: jax.Array,
                    appended0: jax.Array) -> MultimapState:
    """Insert this batch's live rows, which the window appends (compacted,
    arrival order) at overall indices [appended0, appended0 + n_live).

    Vectorized intra-batch chaining: one [B] sort by bucket; within a bucket
    run rows link oldest <- newest, the run's oldest links to the bucket's
    previous head, and each run's END (the newest row) becomes the head —
    one duplicate-free scatter per array, no atomics.
    """
    C = mm.nexts.shape[0]
    H = mm.heads.shape[0]
    B = hashes.shape[0]
    # mirror compact_packed: live rows first, stable → arrival order
    order = stable_partition_order(live)
    hashes = hashes[order]
    valid = live[order]
    j = jnp.arange(B, dtype=jnp.int32)
    seq = (appended0.astype(jnp.uint32) + j.astype(jnp.uint32))
    base = (appended0 % C).astype(jnp.int32)
    pos = base + j
    pos = jnp.where(pos >= C, pos - C, pos)  # base + j < 2C always
    bucket = (hashes & jnp.uint32(H - 1)).astype(jnp.int32)

    sortkey = jnp.where(valid, bucket, jnp.int32(H))
    run = stable_argsort_bounded(sortkey)  # bounded non-negative: radix on CPU
    b_s = sortkey[run]
    seq_s = seq[run]
    hash_s = hashes[run]
    pos_s = pos[run]
    same_as_prev = jnp.concatenate(
        [jnp.zeros((1,), bool), b_s[1:] == b_s[:-1]])
    old_head = mm.heads[jnp.clip(b_s, 0, H - 1)]
    prev_pos = jnp.concatenate(
        [jnp.full((1,), -1, jnp.int32), pos_s[:-1]])
    next_val = jnp.where(same_as_prev, prev_pos, old_head)

    dest = jnp.where(b_s < H, pos_s, jnp.int32(C))
    nexts = mm.nexts.at[dest].set(next_val, mode="drop")
    slot_hash = mm.slot_hash.at[dest].set(hash_s, mode="drop")
    slot_seq = mm.slot_seq.at[dest].set(seq_s, mode="drop")
    is_end = jnp.concatenate(
        [b_s[1:] != b_s[:-1], jnp.ones((1,), bool)]) & (b_s < H)
    hdest = jnp.where(is_end, b_s, jnp.int32(H))
    heads = mm.heads.at[hdest].set(pos_s, mode="drop")
    return MultimapState(heads, nexts, slot_hash, slot_seq)


def multimap_probe(mm: MultimapState, probe_hash: jax.Array,
                   probe_valid: jax.Array, appended: jax.Array,
                   window_len: jax.Array, k_max: int):
    """Walk bucket chains for each probe lane; k_max candidates max.

    Liveness is the u32 age test `0 < appended - slot_seq <= window_len`,
    and the walk additionally requires ages to STRICTLY INCREASE: a chain
    diverted through an overwritten slot jumps to a newer row, the age
    drops, and the walk stops — no stale or duplicate candidates.

    Returns (cand_pos i32[B,K] ring positions oldest-first, cand_ok
    bool[B,K], truncated i32 — probe lanes whose chain still had live
    entries after k_max steps, i.e. potential matches never examined).
    """
    H = mm.heads.shape[0]
    app32 = appended.astype(jnp.uint32)
    wlen = window_len.astype(jnp.uint32)
    bucket = (probe_hash & jnp.uint32(H - 1)).astype(jnp.int32)
    pos = jnp.where(probe_valid, mm.heads[bucket], jnp.int32(-1))
    alive = probe_valid
    prev_age = jnp.zeros_like(app32, shape=pos.shape)
    cands, oks = [], []
    for _ in range(k_max):
        ok_pos = alive & (pos >= 0)
        p = jnp.where(ok_pos, pos, 0)
        age = app32 - mm.slot_seq[p]
        live = ok_pos & (age > prev_age) & (age <= wlen)
        match = live & (mm.slot_hash[p] == probe_hash)
        cands.append(jnp.where(match, p, jnp.int32(0)))
        oks.append(match)
        alive = live
        prev_age = age
        pos = mm.nexts[p]
    # truncation = the (k_max+1)-th chain entry is genuinely LIVE (one extra
    # age probe, no emission) — a dead or diverted tail is not a lost match
    ok_pos = alive & (pos >= 0)
    p = jnp.where(ok_pos, pos, 0)
    age = app32 - mm.slot_seq[p]
    truncated = jnp.sum(ok_pos & (age > prev_age) & (age <= wlen),
                        dtype=jnp.int32)
    # chains run newest → oldest; reverse so pair emission (and k_max
    # truncation) is oldest-first like the sorted probe path
    cand_pos = jnp.stack(cands[::-1], axis=1)
    cand_ok = jnp.stack(oks[::-1], axis=1)
    return cand_pos, cand_ok, truncated


def probe_equi_mm(plan: JoinPlan, probe_scope: Scope, probe_valid: jax.Array,
                  mm: MultimapState, appended: jax.Array,
                  window_len: jax.Array, k_max: int):
    """`probe_equi` against an incrementally maintained multimap: no build
    sort, no full-ring hash — only chain walks. Returns
    (probe_lane[P], build_row[P] i32 ring positions, pair_valid[P],
    truncated) with P = B*k_max."""
    B = probe_valid.shape[0]
    pkeys = _hash_exprs(plan.probe_keys, probe_scope)
    cand_pos, cand_ok, truncated = multimap_probe(
        mm, pkeys, probe_valid, appended, window_len, k_max)
    probe_lane = jnp.broadcast_to(
        jnp.arange(B)[:, None], (B, k_max)).reshape(-1)
    return probe_lane, cand_pos.reshape(-1), cand_ok.reshape(-1), truncated


def probe_cross(probe_valid: jax.Array, build_valid: jax.Array, k_max: int):
    """All (probe, build) candidates, bounded to the first k_max valid build
    rows per probe lane (small build sides only)."""
    B = probe_valid.shape[0]
    C = build_valid.shape[0]
    # rank of each build row among valid rows
    rank = jnp.cumsum(build_valid.astype(jnp.int32)) - 1
    # k-th valid build row index
    order = stable_partition_order(build_valid)  # valid rows first
    kth = order[jnp.clip(jnp.arange(k_max), 0, C - 1)]
    n_valid = jnp.sum(build_valid.astype(jnp.int32))
    kv = jnp.arange(k_max) < n_valid
    probe_lane = jnp.broadcast_to(jnp.arange(B)[:, None], (B, k_max)).reshape(-1)
    build_row = jnp.broadcast_to(kth[None, :], (B, k_max)).reshape(-1)
    pair_valid = (probe_valid[:, None] & kv[None, :]).reshape(-1)
    return probe_lane, build_row, pair_valid
