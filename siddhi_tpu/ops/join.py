"""Device join kernels (reference: core/query/input/stream/join/JoinProcessor.java:45).

The reference walks each arriving event through `find()` on the opposite
window/table with a CompiledCondition (per-event linked-list probe, optionally
index-accelerated by the table's CollectionExecutors). The TPU redesign probes
a whole micro-batch at once with two strategies chosen at plan time:

- **equi join** (the common case; BASELINE config 5): equality conjuncts
  `A.x == B.y` are extracted from the ON condition; build-side rows are
  key-hash sorted per probe and candidates located by `searchsorted`, bounded
  to K candidates per probe lane. Hashes only generate candidates — the exact
  ON condition re-verifies every pair, so hash collisions cannot produce false
  matches. This is a sort-merge join: one sort of the build ring + one
  binary-search per probe lane, all inside the query's fused XLA program.
- **cross join** fallback for ON conditions with no equality conjunct: a
  [B, C] mask with per-row top-K selection. Requires a small build side.

Both produce a fixed-width pair block: [B*K] matched lanes (+[B] outer lanes
for left/right/full outer), each pair carrying both frames' columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from .search import searchsorted32

from ..core import dtypes
from ..errors import SiddhiAppCreationError
from ..query_api.definition import AttributeType
from ..query_api.expression import And, Compare, CompareOp, Expression, Variable
from .expr_compile import CompiledExpr, Scope, TypeResolver, compile_expression
from .groupby import hash_columns32

BIGKEY = jnp.uint32(0xFFFFFFFF)


def split_conjuncts(expr: Optional[Expression]) -> list[Expression]:
    if expr is None:
        return []
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def frames_of(expr: Expression, resolver: TypeResolver) -> set:
    """Frame refs referenced by an expression (resolving unqualified vars)."""
    out: set = set()

    def walk(e):
        if isinstance(e, Variable):
            ref, _, _ = resolver.resolve(e)
            out.add(ref if ref is not None else resolver.default_frame)
            return
        for attr in ("left", "right", "expression"):
            sub = getattr(e, attr, None)
            if isinstance(sub, Expression):
                walk(sub)
        for p in getattr(e, "parameters", ()) or ():
            if isinstance(p, Expression):
                walk(p)

    walk(expr)
    return out


@dataclass
class JoinPlan:
    """Extracted equi-keys + residual condition for one (probe, build) pair."""

    probe_keys: list  # CompiledExpr evaluated on the probe frame
    build_keys: list  # CompiledExpr evaluated on the build frame
    residual: Optional[CompiledExpr]  # full ON condition (pair-verified)


def plan_join(on: Optional[Expression], probe_frame: str, build_frame: str,
              resolver: TypeResolver, registry) -> JoinPlan:
    probe_keys: list = []
    build_keys: list = []
    for conj in split_conjuncts(on):
        if isinstance(conj, Compare) and conj.op == CompareOp.EQUAL:
            lf = frames_of(conj.left, resolver)
            rf = frames_of(conj.right, resolver)
            if lf <= {probe_frame} and rf <= {build_frame}:
                probe_keys.append(compile_expression(conj.left, resolver, registry))
                build_keys.append(compile_expression(conj.right, resolver, registry))
                continue
            if lf <= {build_frame} and rf <= {probe_frame}:
                probe_keys.append(compile_expression(conj.right, resolver, registry))
                build_keys.append(compile_expression(conj.left, resolver, registry))
                continue
    residual = compile_expression(on, resolver, registry) if on is not None else None
    if residual is not None and residual.type != AttributeType.BOOL:
        raise SiddhiAppCreationError("join ON condition must be boolean")
    return JoinPlan(probe_keys, build_keys, residual)


def _hash_exprs(keys: Sequence[CompiledExpr], scope: Scope) -> jax.Array:
    # avoid colliding with the BIGKEY invalid sentinel
    h = hash_columns32([k(scope) for k in keys])
    return jnp.where(h == BIGKEY, jnp.uint32(0xFFFFFFFE), h)


def probe_equi(plan: JoinPlan, probe_scope: Scope, probe_valid: jax.Array,
               build_cols: dict, build_ts: jax.Array, build_valid: jax.Array,
               build_frame: str, k_max: int):
    """Candidate pairs via sort-merge on key hashes.

    Returns (probe_lane[P], build_row[P], pair_valid[P]) with P = B*k_max.
    """
    B = probe_valid.shape[0]
    C = build_ts.shape[0]

    bscope = Scope()
    bscope.add_frame(build_frame, build_cols, build_ts, build_valid, default=True)
    bkeys = jnp.where(build_valid, _hash_exprs(plan.build_keys, bscope), BIGKEY)
    pkeys = _hash_exprs(plan.probe_keys, probe_scope)

    order = jnp.argsort(bkeys, stable=True)  # invalid rows sort last
    sorted_keys = bkeys[order]
    start = searchsorted32(sorted_keys, pkeys, side="left")

    k = jnp.arange(k_max)
    pos = start[:, None] + k[None, :]  # [B,K]
    pos_c = jnp.clip(pos, 0, C - 1)
    cand_valid = (pos < C) & (sorted_keys[pos_c] == pkeys[:, None]) & \
        probe_valid[:, None]
    build_row = order[pos_c]  # [B,K]

    probe_lane = jnp.broadcast_to(jnp.arange(B)[:, None], (B, k_max)).reshape(-1)
    return probe_lane, build_row.reshape(-1), cand_valid.reshape(-1)


def compact_pairs(probe_lane: jax.Array, build_row: jax.Array,
                  pair_valid: jax.Array, pair_cap: int):
    """Compact the sparse [B*k_max] candidate block to `pair_cap` lanes.

    Matches are typically ~1 per probe event, so downstream frame gathers,
    residual verification, and the selector would otherwise run at k_max x
    the real pair count. One cumsum + one 2-word row scatter; candidate
    order (probe-lane major) is preserved, keeping emission order intact.
    Pairs beyond pair_cap are dropped (bounded fan-out, like k_max — size
    via dtypes.config.join_pair_cap_factor)."""
    pos = jnp.cumsum(pair_valid.astype(jnp.int32)) - 1
    dest = jnp.where(pair_valid & (pos < pair_cap), pos, pair_cap)
    packed = jnp.stack([probe_lane.astype(jnp.int32),
                        build_row.astype(jnp.int32)], axis=1)
    rows = jnp.zeros((pair_cap, 2), jnp.int32).at[dest].set(
        packed, mode="drop")
    n = jnp.minimum(jnp.sum(pair_valid, dtype=jnp.int32), pair_cap)
    pv = jnp.arange(pair_cap, dtype=jnp.int32) < n
    return rows[:, 0], rows[:, 1], pv


def probe_cross(probe_valid: jax.Array, build_valid: jax.Array, k_max: int):
    """All (probe, build) candidates, bounded to the first k_max valid build
    rows per probe lane (small build sides only)."""
    B = probe_valid.shape[0]
    C = build_valid.shape[0]
    # rank of each build row among valid rows
    rank = jnp.cumsum(build_valid.astype(jnp.int32)) - 1
    # k-th valid build row index
    order = jnp.argsort(~build_valid, stable=True)  # valid rows first
    kth = order[jnp.clip(jnp.arange(k_max), 0, C - 1)]
    n_valid = jnp.sum(build_valid.astype(jnp.int32))
    kv = jnp.arange(k_max) < n_valid
    probe_lane = jnp.broadcast_to(jnp.arange(B)[:, None], (B, k_max)).reshape(-1)
    build_row = jnp.broadcast_to(kth[None, :], (B, k_max)).reshape(-1)
    pair_valid = (probe_valid[:, None] & kv[None, :]).reshape(-1)
    return probe_lane, build_row, pair_valid
