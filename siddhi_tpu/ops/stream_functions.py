"""Stream-function SPI — N-in/M-out batch transforms in FROM chains.

Reference: core/query/processor/stream/StreamFunctionProcessor.java (extension
SPI appending computed attributes to each event; e.g.
Pol2CartStreamFunctionProcessor, LogStreamProcessor). TPU form: a stream
function maps whole columnar batches — `fn(arg_arrays...) -> dict[new_attr ->
array]` traced inside the query's jitted step, appending columns to the frame.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import jax.numpy as jnp

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from ..query_api.definition import AttributeType


@dataclass
class StreamFunctionSpec:
    """Compiled stream function: `apply(*arg_cols) -> {name: col}`;
    `new_attrs` extends the stream schema."""

    apply: Callable
    new_attrs: list  # [(name, AttributeType)]


@dataclass
class StreamFunctionFactory:
    """SPI: make(arg_types: tuple[AttributeType]) -> StreamFunctionSpec."""

    make: Callable


def _make_pol2cart(arg_types):
    """pol2Cart(theta, rho [, z]) -> x, y [, z] (reference:
    Pol2CartStreamFunctionProcessor.java)."""
    if len(arg_types) < 2:
        raise SiddhiAppCreationError("pol2Cart needs (theta, rho)")

    def apply(theta, rho, *z):
        x = rho * jnp.cos(jnp.deg2rad(theta))
        y = rho * jnp.sin(jnp.deg2rad(theta))
        out = {"x": x, "y": y}
        if z:
            out["z"] = z[0]
        return out

    new = [("x", AttributeType.DOUBLE), ("y", AttributeType.DOUBLE)]
    if len(arg_types) > 2:
        new.append(("z", AttributeType.DOUBLE))
    return StreamFunctionSpec(apply, new)


def _make_log(arg_types):
    """log(...) — the reference's LogStreamProcessor prints events; device
    batches cannot print per event, so this is a pass-through marker (host
    logging happens at callbacks)."""

    def apply(*args):
        return {}

    return StreamFunctionSpec(apply, [])


def register_all() -> None:
    GLOBAL.register(ExtensionKind.STREAM_FUNCTION, "", "pol2Cart",
                    StreamFunctionFactory(_make_pol2cart))
    GLOBAL.register(ExtensionKind.STREAM_FUNCTION, "", "log",
                    StreamFunctionFactory(_make_log))


register_all()
