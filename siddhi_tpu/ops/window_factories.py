"""Window extension registrations (reference: the @Extension window processors
under core/query/processor/stream/window/). Each factory receives the stream's
column layout, the junction batch capacity, evaluated constant parameters, and
whether the query consumes expired events."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from .windows import (
    LengthBatchWindow,
    PassThroughWindow,
    SessionWindow,
    SlidingWindow,
    SortWindow,
    TimeBatchWindow,
    WindowOp,
)


@dataclass
class WindowFactory:
    make: Callable  # (layout, batch_cap, params: list, expired_on: bool) -> WindowOp


def _int_param(params, i, name, what="window"):
    if len(params) <= i:
        raise SiddhiAppCreationError(f"{what} {name!r} needs parameter {i + 1}")
    v = params[i]
    if not isinstance(v, int):
        raise SiddhiAppCreationError(f"{name} parameter {i + 1} must be int/time, got {v!r}")
    return v


def _make_length(layout, batch_cap, params, expired_on):
    n = _int_param(params, 0, "length")
    return SlidingWindow(layout, batch_cap, length=n)


def _make_length_batch(layout, batch_cap, params, expired_on):
    n = _int_param(params, 0, "lengthBatch")
    return LengthBatchWindow(layout, batch_cap, n, expired_on=expired_on)


def _make_time(layout, batch_cap, params, expired_on):
    w = _int_param(params, 0, "time")
    return SlidingWindow(layout, batch_cap, time_ms=w)


def _make_time_batch(layout, batch_cap, params, expired_on):
    w = _int_param(params, 0, "timeBatch")
    start = params[1] if len(params) > 1 else None
    return TimeBatchWindow(layout, batch_cap, w, expired_on=expired_on,
                           start_time=start)


def _make_time_length(layout, batch_cap, params, expired_on):
    w = _int_param(params, 0, "timeLength")
    n = _int_param(params, 1, "timeLength")
    return SlidingWindow(layout, batch_cap, time_ms=w, length=n, capacity=n)


def _make_delay(layout, batch_cap, params, expired_on):
    w = _int_param(params, 0, "delay")
    return SlidingWindow(layout, batch_cap, time_ms=w, is_delay=True)


def _make_external_time(layout, batch_cap, params, expired_on):
    # externalTime(tsAttr, W) — first param is a Variable (attr ref).
    # Watermark semantics: expiry advances with max-seen tsAttr; under
    # @app:eventTime the query runtime sets .lateness_ms so the watermark
    # trails max-seen by the allowed lateness (panes stay open for rows the
    # ingress gate still buffers) and the SL116 lint guards the
    # multi-producer case where max-seen alone is nondeterministic.
    from ..query_api.expression import Variable
    if len(params) < 2 or not isinstance(params[0], Variable):
        raise SiddhiAppCreationError(
            "externalTime needs (timestampAttr, window.time)")
    w = params[1]
    return SlidingWindow(layout, batch_cap, time_ms=w,
                         ts_attr=params[0].attribute)


def _make_external_time_batch(layout, batch_cap, params, expired_on):
    from ..query_api.expression import Variable
    if len(params) < 2 or not isinstance(params[0], Variable):
        raise SiddhiAppCreationError(
            "externalTimeBatch needs (timestampAttr, window.time [, startTime])")
    w = params[1]
    start = params[2] if len(params) > 2 else None
    return TimeBatchWindow(layout, batch_cap, w, expired_on=expired_on,
                           start_time=start, ts_attr=params[0].attribute)


def _make_session(layout, batch_cap, params, expired_on):
    from ..query_api.expression import Variable
    gap = _int_param(params, 0, "session")
    if len(params) > 1:
        key = params[1]
        if not isinstance(key, Variable):
            raise SiddhiAppCreationError(
                "session key must be a stream attribute")
        from .windows_extra import KeyedSessionWindow
        return KeyedSessionWindow(layout, batch_cap, gap, key.attribute)
    return SessionWindow(layout, batch_cap, gap)


def _make_sort(layout, batch_cap, params, expired_on):
    from ..query_api.expression import Variable
    n = _int_param(params, 0, "sort")
    keys = []
    i = 1
    while i < len(params):
        v = params[i]
        if not isinstance(v, Variable):
            raise SiddhiAppCreationError("sort() keys must be attributes")
        order = 1
        if i + 1 < len(params) and isinstance(params[i + 1], str):
            order = -1 if params[i + 1].lower() == "desc" else 1
            i += 1
        keys.append((v.attribute, order))
        i += 1
    if not keys:
        raise SiddhiAppCreationError("sort() needs at least one key attribute")
    return SortWindow(layout, batch_cap, n, keys)


def _make_cron(layout, batch_cap, params, expired_on):
    from .windows_extra import CronWindow
    if len(params) != 1 or not isinstance(params[0], str):
        raise SiddhiAppCreationError("cron window needs ('<cron expression>')")
    return CronWindow(layout, batch_cap, params[0], expired_on=expired_on)


def _make_hopping(layout, batch_cap, params, expired_on):
    from .windows_extra import HoppingWindow
    w = _int_param(params, 0, "hopping")
    h = _int_param(params, 1, "hopping")
    return HoppingWindow(layout, batch_cap, w, h)


def _frequent_keys(params, start):
    from ..query_api.expression import Variable
    keys = []
    for p in params[start:]:
        if not isinstance(p, Variable):
            raise SiddhiAppCreationError("frequent key parameters must be attributes")
        keys.append(p.attribute)
    return keys or None


def _make_frequent(layout, batch_cap, params, expired_on):
    from .windows_extra import FrequentWindow
    n = _int_param(params, 0, "frequent")
    return FrequentWindow(layout, batch_cap, n,
                          key_attrs=_frequent_keys(params, 1))


def _make_lossy_frequent(layout, batch_cap, params, expired_on):
    from .windows_extra import FrequentWindow
    if not params or not isinstance(params[0], float):
        raise SiddhiAppCreationError(
            "lossyFrequent needs (supportThreshold [, errorBound] [, attrs...])")
    support = params[0]
    error = params[1] if len(params) > 1 and isinstance(params[1], float) else support / 10.0
    start = 2 if len(params) > 1 and isinstance(params[1], float) else 1
    if not 0.0 < support < 1.0:
        raise SiddhiAppCreationError(
            f"lossyFrequent supportThreshold must be in (0, 1), got {support}")
    if not 0.0 < error < support:
        raise SiddhiAppCreationError(
            f"lossyFrequent errorBound must be in (0, supportThreshold), got {error}")
    n_slots = max(int(1.0 / error), 16)
    return FrequentWindow(layout, batch_cap, n_slots,
                          key_attrs=_frequent_keys(params, start),
                          support=support, error=error, lossy=True)


def _make_expression(layout, batch_cap, params, expired_on):
    """expression(condition): monotone-suffix conditions take the fully
    vectorized binary-search path; anything else runs the reference's exact
    pop-loop sequentially on device (expression_general)."""
    from .expression_general import GeneralExpressionWindow
    from .expression_window import ExpressionWindow
    if not params or not isinstance(params[0], str):
        raise SiddhiAppCreationError(
            "expression window needs a condition string, e.g. "
            "expression('count() <= 20')")
    try:
        w = ExpressionWindow(layout, batch_cap, params[0])
        # the binary-search path is exact only when the metric sequence is
        # monotone BY CONSTRUCTION: count() and event-timestamp spans
        # (watermark ordering). sum()/attr-span monotonicity is a data
        # property — those run the exact sequential path
        if all(c.kind in ("count", "ts_span") for c in w.conjuncts):
            return w
    except SiddhiAppCreationError:
        pass
    return GeneralExpressionWindow(layout, batch_cap, params[0])


def _make_expression_batch(layout, batch_cap, params, expired_on):
    """expressionBatch('count() <= N') is exactly lengthBatch(N); every
    other condition segments greedily with one device check per arrival
    (reference: ExpressionBatchWindowProcessor.java:288-347)."""
    from ..compiler import parse_expression
    from .expression_general import GeneralExpressionBatchWindow
    from .expression_window import plan_expression
    if not params or not isinstance(params[0], str):
        raise SiddhiAppCreationError(
            "expressionBatch window needs a condition string")
    include_trigger = False
    if len(params) > 1:
        if isinstance(params[1], bool):
            include_trigger = params[1]
        else:
            raise SiddhiAppCreationError(
                "expressionBatch second parameter (includeTriggeringEvent) "
                "must be a constant bool")
    if len(params) > 2:
        raise SiddhiAppCreationError(
            "expressionBatch stream-input-events mode (3rd parameter) is "
            "not supported on this engine")
    try:
        conjuncts = plan_expression(parse_expression(params[0]), layout)
    except SiddhiAppCreationError:
        conjuncts = None
    if (conjuncts is not None and len(conjuncts) == 1
            and conjuncts[0].kind == "count" and not include_trigger):
        c = conjuncts[0]
        n = int(c.limit) - (1 if c.strict else 0)
        if n < 1:
            raise SiddhiAppCreationError(
                "expressionBatch count bound admits no events")
        return LengthBatchWindow(layout, batch_cap, n, expired_on=expired_on)
    return GeneralExpressionBatchWindow(layout, batch_cap, params[0],
                                        include_trigger=include_trigger)


def register_all() -> None:
    from ..extension.registry import ExtensionMeta, Parameter

    def reg(name, make, desc="", params=(), repeat_last=False):
        GLOBAL.register(
            ExtensionKind.WINDOW, "", name, WindowFactory(make),
            meta=ExtensionMeta(description=desc,
                               parameters=tuple(params),
                               repeat_last=repeat_last))

    P = Parameter
    reg("length", _make_length,
        "Sliding window holding the last N events.",
        [P("window.length", ("int",), doc="number of events retained")])
    reg("expression", _make_expression,
        "Sliding window retaining events while the expression holds.",
        [P("expression", ("string", "bool"),
           doc="retain condition over the window contents")])
    reg("expressionBatch", _make_expression_batch,
        "Tumbling window flushing when the expression turns false.",
        [P("expression", ("string", "bool"),
           doc="retain condition; flush on violation"),
         P("include.triggering.event", ("bool",), optional=True,
           default=False,
           doc="start the next batch with the violating arrival"),
         P("stream.current.event", ("bool",), optional=True, default=False,
           doc="reference stream-mode flag (rejected with guidance)")])
    reg("lengthBatch", _make_length_batch,
        "Tumbling window emitting every N events.",
        [P("window.length", ("int",), doc="events per batch")])
    reg("time", _make_time,
        "Sliding window holding events of the last T time units.",
        [P("window.time", ("time",), doc="retention period")])
    reg("timeBatch", _make_time_batch,
        "Tumbling window flushing every T time units.",
        [P("window.time", ("time",), doc="batch period"),
         P("start.time", ("int", "time"), optional=True, default=0,
           doc="bucket epoch offset")])
    reg("timeLength", _make_time_length,
        "Sliding window bounded by BOTH time and count.",
        [P("window.time", ("time",), doc="retention period"),
         P("window.length", ("int",), doc="max events retained")])
    reg("delay", _make_delay,
        "Emits events after a fixed delay.",
        [P("window.delay", ("time",), doc="delay period")])
    reg("batch", lambda l, b, p, e: PassThroughWindow(l, b) if not p
        else LengthBatchWindow(l, b, p[0], expired_on=e),
        "Chunk-boundary tumbling window.",
        [P("window.length", ("int",), optional=True,
           doc="events per batch (default: the arrival chunk)")])
    reg("externalTime", _make_external_time,
        "Sliding time window over an event-attribute clock.",
        [P("timestamp", ("attribute",), doc="the time attribute"),
         P("window.time", ("time",), doc="retention period")])
    reg("externalTimeBatch", _make_external_time_batch,
        "Tumbling time window over an event-attribute clock.",
        [P("timestamp", ("attribute",), doc="the time attribute"),
         P("window.time", ("time",), doc="batch period"),
         P("start.time", ("int", "time"), optional=True,
           doc="first bucket start"),
         P("timeout", ("time",), optional=True,
           doc="flush timeout past the bucket end")])
    reg("session", _make_session,
        "Session window keyed by a gap of inactivity.",
        [P("window.session", ("time",), doc="session gap"),
         P("window.key", ("attribute",), optional=True,
           doc="per-key sessions"),
         P("window.allowedlatency", ("time",), optional=True,
           doc="late-arrival grace period")])
    reg("sort", _make_sort,
        "Keeps the top-N events by sort order.",
        [P("window.length", ("int",), doc="events retained"),
         P("attribute", ("attribute", "string"), optional=True,
           doc="sort key(s), each optionally followed by 'asc'/'desc'")],
        repeat_last=True)
    reg("cron", _make_cron,
        "Tumbling window flushing on a cron schedule.",
        [P("cron.expression", ("string",), doc="quartz-layout cron")])
    reg("hopping", _make_hopping,
        "Hopping time window (period, hop).",
        [P("window.time", ("time",), doc="window span"),
         P("hop.time", ("time",), doc="hop step")])
    reg("frequent", _make_frequent,
        "Retains the most frequent event variants (Misra-Gries).",
        [P("event.count", ("int",), doc="variants tracked"),
         P("attribute", ("attribute",), optional=True,
           doc="key attributes (default: all)")],
        repeat_last=True)
    reg("lossyFrequent", _make_lossy_frequent,
        "Lossy-counting frequent-variant window.",
        [P("support.threshold", ("double",), doc="min relative frequency"),
         # position 2 is either the error bound OR already an attribute
         # (the factory detects which — error.bound is optional-positional)
         P("error.bound", ("double", "attribute"), optional=True,
           doc="counting error bound, or the first key attribute"),
         P("attribute", ("attribute",), optional=True,
           doc="key attributes (default: all)")],
        repeat_last=True)


register_all()
