"""Removal-capable min/max over sliding windows.

Reference: core/query/selector/attribute/aggregator/
MinAttributeAggregatorExecutor.java:132-154 (and Max...) keep a sorted
multiset so processRemove can surface the next extremum when the current one
expires. A multiset is hostile to SIMD; the TPU observation is that a FIFO
sliding window's contents at any point in event order are a CONTIGUOUS RANGE
of the arrival sequence, so per-event extrema are range-min/max queries:

  1. materialize the window's arrival-order value sequence (ring rolled to
     the expiry frontier via one doubled-ring slice + this batch's arrivals
     scattered behind it);
  2. build a sparse table — log2(N) levels of shifted min/max, pure vector
     ops;
  3. each chunk lane's (l, r) range comes from running counts of EXPIRED /
     CURRENT lanes in emission order; its extremum is the classic two-probe
     RMQ lookup, one gather pair for the whole chunk.

Per-step cost is O(N log N) vector work with no data-dependent control flow.

GROUPED variants add one stable 3-key sort by (group-hash64, position):
per-group rows become contiguous runs, each lane's range endpoints land by
vectorized binary search on the composite key, and the same two-probe RMQ
applies (a range never crosses its group's run, so boundary-mixing sparse
levels are harmless). 64-bit group hashes make cross-group merges a 2^-64
event — the engine-wide hashing policy (ops/groupby.hash_columns).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.event import EventBatch, EventType
from .groupby import _op_max, _op_min


def sliding_extrema_lanes(
    op: str,  # 'min' | 'max'
    ring_vals: jax.Array,  # [C] arg values over ring rows, slot order
    expired: jax.Array,  # int64 pre-step expiry frontier (overall idx)
    appended: jax.Array,  # int64 pre-step append frontier
    chunk: EventBatch,  # the window's emission chunk
    cur_vals: jax.Array,  # [L] arg values over chunk rows
) -> jax.Array:
    """Per-chunk-lane window extremum after that lane's add/remove applies."""
    combine, identity = (_op_min if op == "min" else _op_max)(ring_vals.dtype)
    C = ring_vals.shape[0]
    L = chunk.capacity
    N = C + L

    winlen0 = (appended - expired).astype(jnp.int32)
    base = (expired % C).astype(jnp.int32)
    arr = jax.lax.dynamic_slice(
        jnp.concatenate([ring_vals, ring_vals]), (base,), (C,))

    is_cur = chunk.valid & (chunk.types == EventType.CURRENT)
    is_exp = chunk.valid & (chunk.types == EventType.EXPIRED)
    cc = jnp.cumsum(is_cur.astype(jnp.int32))
    ce = jnp.cumsum(is_exp.astype(jnp.int32))

    A = jnp.concatenate([arr, jnp.full((L,), identity, ring_vals.dtype)])
    dest = jnp.where(is_cur, winlen0 + cc - 1, N)
    A = A.at[dest].set(cur_vals.astype(ring_vals.dtype), mode="drop")

    # sparse table: level k holds extrema over [i, i + 2^k)
    levels = [A]
    span = 1
    while span < N:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[span:], jnp.full((span,), identity, prev.dtype)])
        levels.append(combine(prev, shifted))
        span *= 2
    M = jnp.stack(levels)  # [n_levels, N]
    flat = M.reshape(-1)

    l = ce  # expired lanes include their own removal
    r = winlen0 + cc  # current lanes include their own arrival
    length = r - l
    k = 31 - jax.lax.clz(jnp.maximum(length, 1))
    off = jnp.left_shift(jnp.int32(1), k)
    g1 = flat[k * N + jnp.clip(l, 0, N - 1)]
    g2 = flat[k * N + jnp.clip(r - off, 0, N - 1)]
    out = combine(g1, g2)
    return jnp.where(length > 0, out, jnp.full_like(out, identity))


def _split64(h: jax.Array):
    """int64/uint64 hash → two u32 word arrays (any consistent total order
    works for grouping; both the sort and the search use this split)."""
    w = jax.lax.bitcast_convert_type(h, jnp.uint32)
    return w[..., 0], w[..., 1]


def grouped_sliding_extrema_lanes(
    op: str,  # 'min' | 'max'
    ring_vals: jax.Array,  # [C] arg values over ring rows, slot order
    ring_gkey: jax.Array,  # [C] 64-bit group hash per ring row
    expired: jax.Array,
    appended: jax.Array,
    chunk: EventBatch,
    cur_vals: jax.Array,  # [L] arg values over chunk rows
    cur_gkey: jax.Array,  # [L] 64-bit group hash per chunk row
) -> jax.Array:
    """Per-chunk-lane extremum over the lane's GROUP within the window
    (reference: per-group AggregatorState multisets in
    Min/MaxAttributeAggregatorExecutor.processRemove)."""
    combine, identity = (_op_min if op == "min" else _op_max)(ring_vals.dtype)
    C = ring_vals.shape[0]
    L = chunk.capacity
    N = C + L

    winlen0 = (appended - expired).astype(jnp.int32)
    base = (expired % C).astype(jnp.int32)
    arr = jax.lax.dynamic_slice(
        jnp.concatenate([ring_vals, ring_vals]), (base,), (C,))
    garr = jax.lax.dynamic_slice(
        jnp.concatenate([ring_gkey, ring_gkey]), (base,), (C,))

    is_cur = chunk.valid & (chunk.types == EventType.CURRENT)
    is_exp = chunk.valid & (chunk.types == EventType.EXPIRED)
    cc = jnp.cumsum(is_cur.astype(jnp.int32))
    ce = jnp.cumsum(is_exp.astype(jnp.int32))

    A = jnp.concatenate([arr, jnp.full((L,), identity, ring_vals.dtype)])
    ah, al = _split64(garr)
    gh = jnp.concatenate([ah, jnp.zeros((L,), jnp.uint32)])
    gl = jnp.concatenate([al, jnp.zeros((L,), jnp.uint32)])
    dest = jnp.where(is_cur, winlen0 + cc - 1, N)
    A = A.at[dest].set(cur_vals.astype(ring_vals.dtype), mode="drop")
    ch, cl = _split64(cur_gkey)
    gh = gh.at[dest].set(ch, mode="drop")
    gl = gl.at[dest].set(cl, mode="drop")
    # stale slots (pos >= winlen0 + total curs) and the unwritten tail sort
    # inside or after their groups but every lane's r-bound excludes them
    pos = jnp.arange(N, dtype=jnp.int32)

    sgh, sgl, spos, sval = jax.lax.sort((gh, gl, pos, A), num_keys=3,
                                        is_stable=False)

    # sparse table over the sorted values
    levels = [sval]
    span = 1
    while span < N:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[span:], jnp.full((span,), identity, prev.dtype)])
        levels.append(combine(prev, shifted))
        span *= 2
    flat = jnp.stack(levels).reshape(-1)

    def lower_bound(tp):
        """First sorted index with key >= (lane's group, tp)."""
        lo = jnp.zeros(tp.shape, jnp.int32)
        hi = jnp.full(tp.shape, N, jnp.int32)
        for _ in range(N.bit_length() + 1):
            mid = (lo + hi) >> 1
            m = jnp.clip(mid, 0, N - 1)
            a1, a2, ap = sgh[m], sgl[m], spos[m]
            lt = (a1 < ch) | ((a1 == ch) & (
                (a2 < cl) | ((a2 == cl) & (ap < tp))))
            take = lo < hi
            lo = jnp.where(take & lt, mid + 1, lo)
            hi = jnp.where(take & ~lt, mid, hi)
        return lo

    l = lower_bound(ce)            # group rows removed so far excluded
    r = lower_bound(winlen0 + cc)  # group rows arrived so far included
    length = r - l
    k = 31 - jax.lax.clz(jnp.maximum(length, 1))
    off = jnp.left_shift(jnp.int32(1), k)
    p1 = flat[k * N + jnp.clip(l, 0, N - 1)]
    p2 = flat[k * N + jnp.clip(r - off, 0, N - 1)]
    out = combine(p1, p2)
    return jnp.where(length > 0, out, jnp.full_like(out, identity))
