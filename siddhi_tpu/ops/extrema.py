"""Removal-capable min/max over sliding windows.

Reference: core/query/selector/attribute/aggregator/
MinAttributeAggregatorExecutor.java:132-154 (and Max...) keep a sorted
multiset so processRemove can surface the next extremum when the current one
expires. A multiset is hostile to SIMD; the TPU observation is that a FIFO
sliding window's contents at any point in event order are a CONTIGUOUS RANGE
of the arrival sequence, so per-event extrema are range-min/max queries:

  1. materialize the window's arrival-order value sequence (ring rolled to
     the expiry frontier via one doubled-ring slice + this batch's arrivals
     scattered behind it);
  2. build a sparse table — log2(N) levels of shifted min/max, pure vector
     ops;
  3. each chunk lane's (l, r) range comes from running counts of EXPIRED /
     CURRENT lanes in emission order; its extremum is the classic two-probe
     RMQ lookup, one gather pair for the whole chunk.

Per-step cost is O(N log N) vector work with no data-dependent control flow.
Grouped variants are not expressible this way (per-group ranges are not
contiguous in arrival order) — the planner rejects them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.event import EventBatch, EventType
from .groupby import _op_max, _op_min


def sliding_extrema_lanes(
    op: str,  # 'min' | 'max'
    ring_vals: jax.Array,  # [C] arg values over ring rows, slot order
    expired: jax.Array,  # int64 pre-step expiry frontier (overall idx)
    appended: jax.Array,  # int64 pre-step append frontier
    chunk: EventBatch,  # the window's emission chunk
    cur_vals: jax.Array,  # [L] arg values over chunk rows
) -> jax.Array:
    """Per-chunk-lane window extremum after that lane's add/remove applies."""
    combine, identity = (_op_min if op == "min" else _op_max)(ring_vals.dtype)
    C = ring_vals.shape[0]
    L = chunk.capacity
    N = C + L

    winlen0 = (appended - expired).astype(jnp.int32)
    base = (expired % C).astype(jnp.int32)
    arr = jax.lax.dynamic_slice(
        jnp.concatenate([ring_vals, ring_vals]), (base,), (C,))

    is_cur = chunk.valid & (chunk.types == EventType.CURRENT)
    is_exp = chunk.valid & (chunk.types == EventType.EXPIRED)
    cc = jnp.cumsum(is_cur.astype(jnp.int32))
    ce = jnp.cumsum(is_exp.astype(jnp.int32))

    A = jnp.concatenate([arr, jnp.full((L,), identity, ring_vals.dtype)])
    dest = jnp.where(is_cur, winlen0 + cc - 1, N)
    A = A.at[dest].set(cur_vals.astype(ring_vals.dtype), mode="drop")

    # sparse table: level k holds extrema over [i, i + 2^k)
    levels = [A]
    span = 1
    while span < N:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[span:], jnp.full((span,), identity, prev.dtype)])
        levels.append(combine(prev, shifted))
        span *= 2
    M = jnp.stack(levels)  # [n_levels, N]
    flat = M.reshape(-1)

    l = ce  # expired lanes include their own removal
    r = winlen0 + cc  # current lanes include their own arrival
    length = r - l
    k = 31 - jax.lax.clz(jnp.maximum(length, 1))
    off = jnp.left_shift(jnp.int32(1), k)
    g1 = flat[k * N + jnp.clip(l, 0, N - 1)]
    g2 = flat[k * N + jnp.clip(r - off, 0, N - 1)]
    out = combine(g1, g2)
    return jnp.where(length > 0, out, jnp.full_like(out, identity))
