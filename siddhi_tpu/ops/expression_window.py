"""expression / expressionBatch windows via monotone-suffix evaluation.

Reference: core/query/processor/stream/window/ExpressionWindowProcessor.java
(395 LoC) re-evaluates an arbitrary expression after every arrival and pops
events from the FRONT while it is false. Arbitrary re-evaluation is a
per-event interpreter loop; the TPU form restricts the condition to
MONOTONE-SUFFIX shapes — conditions that can only become true by dropping
old events — for which the retained window after each arrival is the largest
valid suffix, and each arrival's expiry frontier is a binary search over
prefix metrics of the arrival sequence:

  count() REL N                  -> frontier = pos + 1 - N
  sum(attr) REL C (attr >= 0)    -> searchsorted over the prefix-sum array
  last.a - first.a REL C         -> searchsorted over the (monotone) values
  eventTimestamp(last) - eventTimestamp(first) REL C -> same on timestamps
  AND of the above               -> max of frontiers

REL is < or <=. Anything else (OR, >, ==, arbitrary attrs) is rejected at
plan time with guidance — matching SURVEY §7's "compiler-friendly control
flow" rule rather than emulating the interpreter loop.

expressionBatch (ExpressionBatchWindowProcessor) keeps accumulating until
the condition would break, then flushes as a batch. Only the count() form
(equivalent to lengthBatch) segments in parallel; the window factory
delegates it and rejects the rest (greedy segmentation by running sums is
inherently sequential).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    MathExpression,
    MathOp,
    Variable,
)
from .search import searchsorted32
from .windows import (
    KIND_CURRENT,
    KIND_EXPIRED,
    SlidingState,
    WindowOp,
    _layout_words,
    _pack_rows,
    _packed_ts,
    _append_packed,
    _fetch_rel_packed,
    _ring_live_mask,
    _sort_chunk_packed,
    _unpack_rows,
    compact_packed,
)


class _Conjunct(NamedTuple):
    kind: str  # 'count' | 'sum' | 'span' | 'ts_span'
    attr: Optional[str]
    limit: float  # effective inclusive limit (REL folded in)
    strict: bool  # True for '<'


def _first_last_attr(e: Expression) -> Optional[str]:
    """`last.a - first.a` -> 'a'; eventTimestamp(last)-eventTimestamp(first)
    -> '' (the ts payload)."""
    if (isinstance(e, MathExpression) and e.op == MathOp.SUBTRACT):
        l, r = e.left, e.right
        if (isinstance(l, Variable) and isinstance(r, Variable)
                and l.stream_id == "last" and r.stream_id == "first"
                and l.attribute == r.attribute):
            return l.attribute
        if (isinstance(l, AttributeFunction) and isinstance(r, AttributeFunction)
                and l.name == "eventTimestamp" and r.name == "eventTimestamp"
                and l.parameters and r.parameters
                and isinstance(l.parameters[0], Variable)
                and isinstance(r.parameters[0], Variable)
                and l.parameters[0].attribute == "last"
                and r.parameters[0].attribute == "first"):
            return ""
    return None


def plan_expression(expr: Expression, layout: dict) -> list[_Conjunct]:
    """Decompose a window condition into monotone conjuncts or reject."""
    if isinstance(expr, And):
        return plan_expression(expr.left, layout) + \
            plan_expression(expr.right, layout)
    if not isinstance(expr, Compare):
        raise SiddhiAppCreationError(
            f"expression window conditions must be AND-ed comparisons; "
            f"got {type(expr).__name__} — see ops/expression_window.py for "
            "the supported monotone forms")
    left, op, right = expr.left, expr.op, expr.right
    if isinstance(left, Constant) and not isinstance(right, Constant):
        # `10 > count()` == `count() < 10`
        flip = {CompareOp.GREATER_THAN: CompareOp.LESS_THAN,
                CompareOp.GREATER_THAN_EQUAL: CompareOp.LESS_THAN_EQUAL}
        if op not in flip:
            raise SiddhiAppCreationError(
                "expression window conditions must bound a window metric "
                "from above (< / <=): only shrinking the window can restore "
                "them (monotone-suffix evaluation)")
        left, op, right = right, flip[op], left
    if op not in (CompareOp.LESS_THAN, CompareOp.LESS_THAN_EQUAL):
        raise SiddhiAppCreationError(
            "expression window conditions must bound a window metric from "
            "above (< / <=): only shrinking the window can restore them "
            "(monotone-suffix evaluation)")
    if not isinstance(right, Constant):
        raise SiddhiAppCreationError(
            "expression window bounds must be constants")
    limit = float(right.value)
    strict = op == CompareOp.LESS_THAN

    if (isinstance(left, AttributeFunction) and left.name == "count"
            and not left.parameters):
        return [_Conjunct("count", None, limit, strict)]
    if (isinstance(left, AttributeFunction) and left.name == "sum"
            and left.parameters and isinstance(left.parameters[0], Variable)):
        attr = left.parameters[0].attribute
        if attr not in layout:
            raise SiddhiAppCreationError(
                f"expression window sum() over unknown attribute {attr!r}")
        return [_Conjunct("sum", attr, limit, strict)]
    fl = _first_last_attr(left)
    if fl is not None:
        if fl == "":
            return [_Conjunct("ts_span", None, limit, strict)]
        if fl not in layout:
            raise SiddhiAppCreationError(
                f"expression window span over unknown attribute {fl!r}")
        return [_Conjunct("span", fl, limit, strict)]
    raise SiddhiAppCreationError(
        "unsupported expression window term; supported monotone forms: "
        "count(), sum(attr) with non-negative values, "
        "last.attr - first.attr (monotone attr), "
        "eventTimestamp(last) - eventTimestamp(first)")


class ExpressionWindow(WindowOp):
    """Sliding expression window: after each arrival, the retained window is
    the largest suffix satisfying every conjunct. Expiry is arrival-driven
    (the reference also re-evaluates only on events for these forms)."""

    def __init__(self, layout: dict, batch_cap: int, condition: str):
        from ..compiler import parse_expression
        self.layout = layout
        self.B = batch_cap
        self.conjuncts = plan_expression(parse_expression(condition), layout)
        self.C = max(dtypes.config.default_window_capacity, batch_cap)
        # count() bounds are statically known: size the ring so the retained
        # window can never wrap past capacity (mirrors length(N) setting
        # C = max(N, batch_cap); sum/span forms have no static bound and
        # rely on the step's monitored overflow counter instead)
        for conj in self.conjuncts:
            if conj.kind == "count":
                self.C = max(self.C, int(conj.limit) + batch_cap)
        self.E = max(batch_cap, 1024)
        self.C = max(self.C, self.E)
        self.chunk_width = self.B + self.E
        self.W = _layout_words(layout)

    def init_state(self) -> SlidingState:
        return SlidingState(
            ring=jnp.zeros((self.W, self.C), jnp.uint32),
            appended=jnp.int64(0),
            expired=jnp.int64(0),
            wm=jnp.int64(-(2**62)),
            overflow=jnp.int64(0),
        )

    def _metric_seq(self, conj: _Conjunct, ring_cols, ring_ts, comp_cols,
                    comp_ts, expired, winlen0, n_valid32, fill):
        """Arrival-order metric values: position r holds the event at overall
        index expired + r; window rows [0, winlen0), then this batch's
        arrivals at [winlen0, winlen0 + n_valid). Dead positions hold `fill`
        (0 for prefix sums, dtype-max to keep span sequences monotone)."""
        C, B = self.C, self.B
        if conj.kind == "ts_span":
            ring_vals, comp_vals = ring_ts, comp_ts
        else:
            ring_vals = ring_cols[conj.attr]
            comp_vals = comp_cols[conj.attr].astype(ring_vals.dtype)
        base = (expired % C).astype(jnp.int32)
        arr = jax.lax.dynamic_slice(
            jnp.concatenate([ring_vals, ring_vals]), (base,), (C,))
        fill = jnp.asarray(fill, arr.dtype)
        arr = jnp.where(jnp.arange(C, dtype=jnp.int32) < winlen0, arr, fill)
        A = jnp.concatenate([arr, jnp.full((B,), fill, arr.dtype)])
        p = jnp.arange(B, dtype=jnp.int32)
        dest = jnp.where(p < n_valid32, winlen0 + p, C + B)
        return A.at[dest].set(comp_vals, mode="drop")

    def _frontiers(self, ring_cols, ring_ts, comp_cols, comp_ts, expired,
                   winlen0, n_valid32, q):
        """Per-arrival expiry frontier via binary searches over prefix
        metrics (the monotone fast path; GeneralExpressionWindow overrides
        this with the exact sequential pop-loop for arbitrary conditions)."""
        B, C = self.B, self.C
        s = jnp.zeros((B,), jnp.int32)
        for conj in self.conjuncts:
            if conj.kind == "count":
                n = int(conj.limit) - (1 if conj.strict else 0)
                if n < 1:
                    raise SiddhiAppCreationError(
                        "expression window count bound admits no events")
                f = q + 1 - jnp.int32(n)
            elif conj.kind == "sum":
                seq = self._metric_seq(conj, ring_cols, ring_ts, comp_cols,
                                       comp_ts, expired, winlen0,
                                       n_valid32, 0)
                # prefix[t] = sum seq[0..t-1]; window [s,q] sum =
                # prefix[q+1] - prefix[s] REL lim -> smallest s with
                # prefix[s] >= (strict: >) prefix[q+1] - lim
                prefix = jnp.concatenate([
                    jnp.zeros((1,), jnp.float64),
                    jnp.cumsum(seq.astype(jnp.float64))])
                tot = prefix[1 + jnp.clip(q, 0, C + B - 1)]
                f = searchsorted32(prefix, tot - conj.limit,
                                   side="right" if conj.strict else "left")
            else:  # span / ts_span over a monotone sequence
                big = (jnp.iinfo(jnp.int64).max
                       if conj.kind == "ts_span" else jnp.inf)
                seq = self._metric_seq(conj, ring_cols, ring_ts, comp_cols,
                                       comp_ts, expired, winlen0,
                                       n_valid32, big)
                lastv = seq[jnp.clip(q, 0, C + B - 1)]
                # need seq[s] >= lastv - lim (strict: > lastv - lim)
                target = lastv - jnp.asarray(conj.limit, seq.dtype)
                f = searchsorted32(seq, target,
                                   side="right" if conj.strict else "left")
            s = jnp.maximum(s, f)
        return s

    def step(self, state: SlidingState, batch: EventBatch, now: jax.Array):
        B, E, C = self.B, self.E, self.C
        comp_mat, n_valid32 = compact_packed(batch, self.layout)
        n_valid = n_valid32.astype(jnp.int64)
        comp_cols, comp_ts = _unpack_rows(comp_mat, self.layout)
        winlen0 = (state.appended - state.expired).astype(jnp.int32)

        # per-arrival expiry frontier s_j (relative to state.expired):
        # the smallest window start keeping every conjunct true after j
        p = jnp.arange(B, dtype=jnp.int32)
        q = winlen0 + p  # arrival j's relative position
        ring_cols, ring_ts = _unpack_rows(state.ring, self.layout)
        s = self._frontiers(ring_cols, ring_ts, comp_cols, comp_ts,
                            state.expired, winlen0, n_valid32, q)
        # frontiers are cumulative: a later arrival can never re-admit
        # events an earlier one expired
        s = jax.lax.associative_scan(jnp.maximum, s)
        s = jnp.clip(s, 0, q + 1)
        s_end = jnp.max(jnp.where(p < n_valid32, s, 0))
        # only E expiry lanes can emit per step: cap the frontier advance and
        # let later steps catch up (their recomputed frontiers still hold) —
        # mass expiry must never drop EXPIRED emissions
        s_end = jnp.minimum(s_end, jnp.int32(E))
        # invalid lanes take the final frontier so the trigger search scans a
        # SORTED array (trailing zeros would break the binary search)
        s_sorted = jnp.where(p < n_valid32, jnp.minimum(s, s_end), s_end)

        appended1 = state.appended + n_valid

        # ---- expiry candidates ----
        pe = jnp.arange(E, dtype=jnp.int32)
        cand_exists = pe < (appended1 - state.expired).astype(jnp.int32)
        cand_mat = _fetch_rel_packed(
            state.ring, comp_mat, state.expired, state.appended, E)
        expires = cand_exists & (pe < s_end)
        # trigger: the FIRST arrival whose frontier passes this candidate;
        # reference pops AFTER processing the arrival, so expired lanes sort
        # just after their trigger arrival
        trig = searchsorted32(s_sorted, pe + 1, side="left")
        emit_ts = jnp.broadcast_to(jnp.asarray(now, jnp.int64), (E,))

        cur_valid = p < n_valid32
        # reference pops AFTER processing the triggering arrival: expired
        # lanes sort just after their trigger (slot 3 of the position, past
        # CURRENT's 2) and before the next arrival
        keys_exp = jnp.clip(trig, 0, B) * 4 + 3
        keys_cur = p * 4 + KIND_CURRENT

        all_hi = jnp.concatenate([keys_exp, keys_cur])
        all_lo = jnp.concatenate([pe, p])
        all_mat = jnp.concatenate([cand_mat, comp_mat], axis=1)
        all_emit = jnp.concatenate([emit_ts, comp_ts])
        all_valid = jnp.concatenate([expires, cur_valid])
        all_types = jnp.concatenate([
            jnp.full((E,), EventType.EXPIRED, jnp.int8),
            jnp.full((B,), EventType.CURRENT, jnp.int8),
        ])
        chunk = _sort_chunk_packed(all_hi, all_lo, all_mat, all_emit,
                                   all_valid, all_types, self.layout,
                                   self.chunk_width)

        new_ring = _append_packed(state.ring, comp_mat, state.appended,
                                  n_valid32)
        # sum/span conjuncts have no static bound: count live rows the ring
        # wrap overwrote (ADVICE r02: count() forms are sized statically)
        expired1 = state.expired + s_end.astype(jnp.int64)
        over0 = jnp.maximum(state.appended - state.expired - C, 0)
        over1 = jnp.maximum(appended1 - expired1 - C, 0)
        new_state = SlidingState(
            ring=new_ring,
            appended=appended1,
            expired=expired1,
            wm=state.wm,
            overflow=state.overflow + jnp.maximum(over1 - over0, 0),
        )
        return new_state, chunk

    def contents(self, state: SlidingState, now: jax.Array):
        ring_cols, ring_ts = _unpack_rows(state.ring, self.layout)
        live = _ring_live_mask(self.C, state.expired, state.appended)
        return ring_cols, ring_ts, live
