"""Output rate limiters (reference: core/query/output/ratelimit/ —
OutputRateLimiter.java:43; event/ First/Last/All-PerEvent, time/ scheduler
driven variants; `output [first|last|all] every N events / T sec`).

Device redesign: a rate limiter is a pure `(state, out_batch, now) ->
(state, emit_batch)` transform appended to the query's jitted step.

- events-N first : emit lanes whose output ordinal % N == 0
- events-N last  : emit lanes whose (ordinal+1) % N == 0
- events-N all   : buffer into an [N] ring; emit complete groups only
- time-T first   : emit the first lane of each T-bucket (immediate)
- time-T last    : hold the latest lane per bucket; emit at bucket close
                   (watermark/heartbeat driven, like the reference Scheduler)
- time-T all     : buffer lanes; emit them all at bucket close

Only CURRENT lanes are rate-limited; EXPIRED lanes pass with their CURRENT
counterparts (the reference sends whole chunks per emission)."""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError
from .search import stable_partition_order
from ..query_api.execution import OutputRate, OutputRateType


class CounterState(NamedTuple):
    count: jax.Array  # int64 emitted-ordinal counter


class BufferState(NamedTuple):
    ring: EventBatch  # [C] buffered lanes
    appended: jax.Array  # int64
    flushed: jax.Array  # int64
    bucket: jax.Array  # int64 current time bucket (time mode)


class RateLimiterOp:
    has_time_semantics = False

    def init_state(self):
        raise NotImplementedError

    def step(self, state, out: EventBatch, now):
        raise NotImplementedError


class PassThroughLimiter(RateLimiterOp):
    def init_state(self):
        return ()

    def step(self, state, out, now):
        return state, out


class EventOrdinalLimiter(RateLimiterOp):
    """first/last every N events: a pure mask on the output ordinal."""

    def __init__(self, n: int, which: str):
        self.n = n
        self.which = which

    def init_state(self):
        return CounterState(jnp.int64(0))

    def step(self, state, out: EventBatch, now):
        live = out.valid & (out.types == EventType.CURRENT)
        rank = jnp.cumsum(live.astype(jnp.int64)) - 1
        ordinal = state.count + rank
        N = jnp.int64(self.n)
        if self.which == "first":
            keep = live & (ordinal % N == 0)
        else:
            keep = live & ((ordinal + 1) % N == 0)
        new_count = state.count + jnp.sum(live.astype(jnp.int64))
        return CounterState(new_count), dataclasses.replace(
            out, valid=out.valid & keep)


class BufferedLimiter(RateLimiterOp):
    """all-every-N-events and the time-driven variants: buffer lanes in a ring
    and release them at group/bucket boundaries."""

    def __init__(self, layout: dict, out_width: int, *,
                 n_events: Optional[int] = None,
                 time_ms: Optional[int] = None,
                 which: str = "all"):
        self.layout = layout
        self.B = out_width
        self.n_events = n_events
        self.time_ms = time_ms
        self.which = which
        self.has_time_semantics = time_ms is not None
        self.C = max(2 * out_width, (n_events or 1) * 2, 1024)

    def init_state(self):
        ring = EventBatch(
            ts=jnp.zeros((self.C,), dtypes.TS_DTYPE),
            cols={k: jnp.zeros((self.C,), dt) for k, dt in self.layout.items()},
            valid=jnp.zeros((self.C,), bool),
            types=jnp.zeros((self.C,), jnp.int8),
        )
        return BufferState(ring, jnp.int64(0), jnp.int64(0), jnp.int64(0))

    def step(self, state: BufferState, out: EventBatch, now):
        C = self.C
        live = out.valid & (out.types == EventType.CURRENT)
        order = stable_partition_order(live)
        n_new = jnp.sum(live.astype(jnp.int64))
        B = out.ts.shape[0]
        # int32 lane math relative to one scalar s64 reduction — TPU has no
        # native s64 ALU, so per-lane int64 %/+ lowers to emulated multi-op
        # sequences (see ops/windows.py _scatter_append)
        base = (state.appended % C).astype(jnp.int32)
        p = jnp.arange(B, dtype=jnp.int32)
        slot = jnp.where(p < n_new.astype(jnp.int32), (base + p) % C, C)
        ring = EventBatch(
            ts=state.ring.ts.at[slot].set(out.ts[order], mode="drop"),
            cols={k: state.ring.cols[k].at[slot].set(out.cols[k][order],
                                                     mode="drop")
                  for k in self.layout},
            valid=state.ring.valid.at[slot].set(live[order], mode="drop"),
            types=state.ring.types.at[slot].set(out.types[order], mode="drop"),
        )
        appended = state.appended + n_new

        if self.time_ms is not None:
            T = jnp.int64(self.time_ms)
            cur_bucket = now // T
            closing = cur_bucket > state.bucket
            if self.which == "last":
                # emit only the latest buffered lane when the bucket closes
                flush_to = jnp.where(closing, appended, state.flushed)
                emit_from = jnp.maximum(state.flushed, flush_to - 1)
            else:
                flush_to = jnp.where(closing, appended, state.flushed)
                emit_from = state.flushed
            new_bucket = jnp.maximum(state.bucket, cur_bucket)
        else:
            N = jnp.int64(self.n_events)
            flush_to = (appended // N) * N
            emit_from = state.flushed
            new_bucket = state.bucket

        # gather [emit_from, flush_to) into an output block of width C.
        # Overflow guard: the ring only retains the newest C appended lanes
        # (ordinals [appended - C, appended)); if a bucket/group accumulated
        # more than C lanes, the oldest were overwritten at append time and
        # emitting their slots would replay newer lanes under stale ordinals.
        # Clamp to the retained range — documented truncation, as CronWindow.
        emit_from = jnp.maximum(jnp.maximum(emit_from, appended - C), 0)
        n_emit = jnp.maximum(flush_to - emit_from, 0).astype(jnp.int32)
        ebase = (emit_from % C).astype(jnp.int32)
        i32 = jnp.arange(C, dtype=jnp.int32)
        sel = i32 < n_emit
        oslot = (ebase + i32) % C
        emitted = EventBatch(
            ts=ring.ts[oslot],
            cols={k: ring.cols[k][oslot] for k in self.layout},
            valid=sel & ring.valid[oslot],
            types=ring.types[oslot],
        )
        new_state = BufferState(ring, appended, flush_to, new_bucket)
        return new_state, emitted


class TimeFirstLimiter(RateLimiterOp):
    """first every T: the first output lane of each bucket passes immediately."""

    has_time_semantics = False  # emission is arrival-driven

    def __init__(self, time_ms: int):
        self.T = time_ms

    def init_state(self):
        return CounterState(jnp.int64(-1))  # last emitted bucket

    def step(self, state, out: EventBatch, now):
        T = jnp.int64(self.T)
        live = out.valid & (out.types == EventType.CURRENT)
        bucket = out.ts // T
        # first live lane in a bucket newer than the last emitted one
        newer = live & (bucket > state.count)
        # first `newer` lane per bucket: sort by (bucket, lane) and mark run
        # starts (O(B log B), no [B,B] mask)
        L = out.ts.shape[0]
        key = jnp.where(newer, bucket, jnp.int64(2**62))
        order = jnp.argsort(key, stable=True)
        sk = key[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
        keep_sorted = first & (sk != jnp.int64(2**62))
        keep = jnp.zeros((L,), bool).at[order].set(keep_sorted)
        top = jnp.max(jnp.where(keep, bucket, jnp.int64(-1)))
        new_last = jnp.maximum(state.count, top)
        return CounterState(new_last), dataclasses.replace(
            out, valid=out.valid & keep)


class SnapshotState(NamedTuple):
    last_cols: dict  # [1] retained last output row
    has: jax.Array  # bool
    bucket: jax.Array  # int64 last observed time bucket


class SnapshotLimiter(RateLimiterOp):
    """`output snapshot every <t>` — periodically re-emits the latest output
    row (reference: snapshot/ SnapshotOutputRateLimiter; the per-group and
    windowed variants — 8 further classes — retain per-key rows and are not
    yet built). Emission rides the watermark like every timer here."""

    has_time_semantics = True

    def __init__(self, layout: dict, time_ms: int):
        self.layout = layout
        self.T = time_ms

    def init_state(self) -> SnapshotState:
        return SnapshotState(
            last_cols={k: jnp.zeros((1,), dt) for k, dt in self.layout.items()},
            has=jnp.bool_(False),
            bucket=jnp.int64(-1),
        )

    def step(self, state: SnapshotState, out: EventBatch, now):
        B = out.ts.shape[0]
        live = out.valid & (out.types == EventType.CURRENT)
        idx = jnp.arange(B)
        last_i = jnp.max(jnp.where(live, idx, -1))
        any_live = last_i >= 0
        g = jnp.clip(last_i, 0, B - 1)
        new_cols = {k: jnp.where(any_live, v[g][None], state.last_cols[k])
                    for k, v in out.cols.items()}

        bucket = now // jnp.int64(self.T)
        first = state.bucket < 0
        # fire on a boundary crossing with the PRE-batch retained row: the
        # snapshot shows state as of the boundary, not rows that arrived with
        # the batch that revealed the crossing (batch-granularity watermark)
        fire = state.has & ~first & (bucket > state.bucket)
        emit = EventBatch(
            ts=jnp.broadcast_to(now[None] if now.ndim == 0 else now, (1,)),
            cols=state.last_cols,
            valid=jnp.broadcast_to(fire, (1,)),
            types=jnp.zeros((1,), jnp.int8))
        # bucket advances on EVERY crossing (idle heartbeats included) so a
        # post-idle event waits for the next boundary instead of firing early
        new_state = SnapshotState(
            last_cols=new_cols, has=state.has | any_live,
            bucket=jnp.where(first, bucket,
                             jnp.maximum(state.bucket, bucket)))
        return new_state, emit


class WindowedSnapshotState(NamedTuple):
    cols: dict  # {name: [Cap]} projected rows currently in the window
    appended: jax.Array  # int64 projected CURRENT rows ever
    expired: jax.Array  # int64 projected EXPIRED rows ever
    bucket: jax.Array  # int64 last observed time bucket
    overflow: jax.Array  # int64 live rows overwritten past capacity


class WindowedSnapshotLimiter(RateLimiterOp):
    """`output snapshot every <t>` on a NON-aggregated window query: each
    tick re-emits EVERY event currently in the window (reference:
    snapshot/WindowedPerSnapshotOutputRateLimiter.java keeps an eventList,
    appending CURRENTs and removing on EXPIREDs).

    The TPU shape: a FIFO ring of the PROJECTED output rows — CURRENT lanes
    append, EXPIRED lanes pop the front. Valid for windows that expire in
    arrival order (length/time/timeLength/delay/externalTime/batch
    families); non-FIFO windows (sort, session, frequent) keep the
    retained-last-row SnapshotLimiter (documented in PARITY.md)."""

    has_time_semantics = True

    def __init__(self, layout: dict, time_ms: int, capacity: int):
        self.layout = layout
        self.T = time_ms
        self.Cap = capacity
        self.chunk_width = capacity

    def init_state(self) -> WindowedSnapshotState:
        Cap = self.Cap
        return WindowedSnapshotState(
            cols={k: jnp.zeros((Cap,), dt) for k, dt in self.layout.items()},
            appended=jnp.int64(0),
            expired=jnp.int64(0),
            bucket=jnp.int64(-1),
            overflow=jnp.int64(0),
        )

    def step(self, state: WindowedSnapshotState, out: EventBatch, now):
        Cap = self.Cap
        cur = out.valid & (out.types == EventType.CURRENT)
        exp = out.valid & (out.types == EventType.EXPIRED)
        n_cur = jnp.sum(cur, dtype=jnp.int64)
        n_exp = jnp.sum(exp, dtype=jnp.int64)

        # --- ring update: CURRENT appends, EXPIRED pops the front ---
        rank = jnp.cumsum(cur.astype(jnp.int32)) - 1
        slot = (state.appended % Cap).astype(jnp.int32) + rank
        slot = jnp.where(slot >= Cap, slot - Cap, slot)
        slot = jnp.where(cur, slot, Cap)
        new_cols = {k: state.cols[k].at[slot].set(out.cols[k], mode="drop")
                    for k in state.cols}
        appended1 = state.appended + n_cur
        expired1 = state.expired + n_exp
        over0 = jnp.maximum(state.appended - state.expired - Cap, 0)
        over1 = jnp.maximum(appended1 - expired1 - Cap, 0)

        # --- tick emission: the snapshot shows the window AS OF the newest
        # crossed boundary — this chunk's adds/removes stamped at or before
        # the boundary apply, later ones wait (lane ts carry arrival/expiry
        # instants, so the split is exact even inside one batch) ---
        bucket = now // jnp.int64(self.T)
        first = state.bucket < 0
        fire = ~first & (bucket > state.bucket)
        boundary_ts = bucket * jnp.int64(self.T)
        n_exp_pre = jnp.sum(exp & (out.ts <= boundary_ts), dtype=jnp.int64)
        n_cur_pre = jnp.sum(cur & (out.ts <= boundary_ts), dtype=jnp.int64)
        lo = state.expired + n_exp_pre
        winlen = (state.appended + n_cur_pre - lo).astype(jnp.int32)
        pe = jnp.arange(Cap, dtype=jnp.int32)
        base = (lo % Cap).astype(jnp.int32)
        row = base + pe
        row = jnp.where(row >= Cap, row - Cap, row)
        emit = EventBatch(
            ts=jnp.broadcast_to(now, (Cap,)),
            cols={k: v[row] for k, v in new_cols.items()},
            valid=fire & (pe < winlen),
            types=jnp.zeros((Cap,), jnp.int8))

        new_state = WindowedSnapshotState(
            cols=new_cols,
            appended=appended1, expired=expired1,
            bucket=jnp.where(first, bucket,
                             jnp.maximum(state.bucket, bucket)),
            overflow=state.overflow + jnp.maximum(over1 - over0, 0),
        )
        return new_state, emit


class ContentsSnapshotState(NamedTuple):
    bucket: jax.Array  # int64 last observed time bucket


class ContentsSnapshotLimiter(RateLimiterOp):
    """`output snapshot every <t>` on a non-aggregated query over a NON-FIFO
    window (sort/session/frequent/cron/hopping): per-arrival output is
    suppressed; each tick re-emits the PROJECTION of the window's live
    contents, read straight from the ring (the FIFO add/remove tracking of
    WindowedSnapshotLimiter cannot follow out-of-order expiry, but the
    window's own findable surface is always exact). Reference:
    snapshot/WindowedPerSnapshotOutputRateLimiter semantics over any
    findable window. Snapshot granularity: contents AS OF the watermark
    that crossed the boundary (batch-granularity, like SnapshotLimiter)."""

    has_time_semantics = True
    #: the query step must call step_contents with the projected window
    #: contents instead of step()
    needs_window_contents = True

    def __init__(self, time_ms: int):
        self.T = time_ms

    def init_state(self) -> ContentsSnapshotState:
        return ContentsSnapshotState(bucket=jnp.int64(-1))

    def step(self, state, out, now):  # pragma: no cover — runtime wires
        raise SiddhiAppCreationError(    # step_contents instead
            "ContentsSnapshotLimiter needs window contents")

    def step_contents(self, state: ContentsSnapshotState,
                      contents: EventBatch, now):
        """`contents.ts` carries each live row's ARRIVAL instant: rows that
        arrived past the fired boundary (same-batch late arrivals) are
        excluded from that boundary's snapshot — exact on arrivals,
        batch-granular on evictions."""
        bucket = now // jnp.int64(self.T)
        first = state.bucket < 0
        fire = ~first & (bucket > state.bucket)
        boundary_ts = bucket * jnp.int64(self.T)
        emit = dataclasses.replace(
            contents,
            ts=jnp.broadcast_to(jnp.asarray(now, contents.ts.dtype),
                                contents.ts.shape),
            valid=contents.valid & fire & (contents.ts <= boundary_ts))
        new_state = ContentsSnapshotState(
            bucket=jnp.where(first, bucket,
                             jnp.maximum(state.bucket, bucket)))
        return new_state, emit


class GroupedSnapshotState(NamedTuple):
    rows: dict  # [G] retained last row per group, per column
    present: jax.Array  # bool[G]
    bucket: jax.Array  # int64 last observed time bucket
    overflow: jax.Array  # int32 lifetime lanes whose group slot exceeded G


class GroupedSnapshotLimiter(RateLimiterOp):
    """`output snapshot every <t> ... group by k` — periodically re-emits the
    latest output row of EVERY group (reference:
    snapshot/GroupByPerSnapshotOutputRateLimiter.java and the aggregation
    variants, whose per-group running aggregate IS the latest row here).

    The selector rides each lane's group slot on GROUP_SLOT_COL; retention
    is one scatter of each batch's last-lane-per-slot. Groups beyond the
    snapshot capacity (config.snapshot_group_capacity) are dropped —
    documented bound."""

    has_time_semantics = True

    def __init__(self, layout: dict, time_ms: int, n_groups: int,
                 group_capacity: int):
        self.layout = layout
        self.T = time_ms
        # the selector's overflow sentinel slot is group_capacity: bounding
        # G by it keeps phantom sentinel rows out of snapshots
        self.G = min(n_groups, group_capacity)

    def init_state(self) -> GroupedSnapshotState:
        G = self.G
        return GroupedSnapshotState(
            rows={k: jnp.zeros((G,), dt) for k, dt in self.layout.items()},
            present=jnp.zeros((G,), bool),
            bucket=jnp.int64(-1),
            overflow=jnp.int32(0),
        )

    def step(self, state: GroupedSnapshotState, out: EventBatch, now):
        from .selector import GROUP_SLOT_COL
        G = self.G
        L = out.ts.shape[0]
        slots = out.cols[GROUP_SLOT_COL]
        live = out.valid & (out.types == EventType.CURRENT) & (slots < G) \
            & (slots >= 0)

        bucket = now // jnp.int64(self.T)
        first = state.bucket < 0
        # fire with the PRE-batch retained rows: the snapshot shows state as
        # of the boundary (matches SnapshotLimiter's boundary semantics)
        fire = ~first & (bucket > state.bucket)
        emit = EventBatch(
            ts=jnp.broadcast_to(jnp.asarray(now, dtypes.TS_DTYPE), (G,)),
            cols=dict(state.rows),
            valid=state.present & jnp.broadcast_to(fire, (G,)),
            types=jnp.zeros((G,), jnp.int8),
        )

        # retain the LAST live lane per slot (deterministic last-wins)
        idx = jnp.arange(L, dtype=jnp.int32)
        slots_c = jnp.clip(slots, 0, G - 1)
        last = jax.ops.segment_max(
            jnp.where(live, idx, -1), slots_c, num_segments=G)
        is_last = live & (idx == last[slots_c])
        dest = jnp.where(is_last, slots, G)
        rows = {k: state.rows[k].at[dest].set(out.cols[k], mode="drop")
                for k in self.layout}
        cur = out.valid & (out.types == EventType.CURRENT)
        new_state = GroupedSnapshotState(
            rows=rows,
            present=state.present.at[dest].set(True, mode="drop"),
            bucket=jnp.where(first, bucket,
                             jnp.maximum(state.bucket, bucket)),
            overflow=state.overflow + jnp.sum(cur & (slots >= G),
                                              dtype=jnp.int32),
        )
        return new_state, emit


def make_rate_limiter(rate: Optional[OutputRate], layout: dict,
                      out_width: int, grouped: bool = False,
                      group_capacity: int = 1 << 20,
                      fifo_window: bool = False,
                      has_aggregates: bool = False,
                      window_capacity: int = 0,
                      contents_window: bool = False) -> RateLimiterOp:
    if rate is None:
        return PassThroughLimiter()
    if rate.type == OutputRateType.SNAPSHOT:
        if rate.time_ms is None:
            raise SiddhiAppCreationError(
                "`output snapshot every ...` needs a time period")
        if fifo_window and not has_aggregates:
            # reference WindowedPerSnapshotOutputRateLimiter (and its
            # GroupBy sibling — grouped non-aggregated queries snapshot the
            # same full contents, per-group lists concatenate to all rows):
            # re-emit the FULL window contents each tick. Cap = the window's
            # own capacity when known (fallback to the config default), but
            # never below the per-step chunk width — the append slot math
            # wraps at most once, so one step's CURRENT lanes must fit.
            cap = max(window_capacity
                      or dtypes.config.snapshot_window_capacity, out_width)
            return WindowedSnapshotLimiter(layout, rate.time_ms, cap)
        if contents_window and not has_aggregates:
            # non-FIFO windows (sort/session/frequent/...): snapshot the
            # ring's live set via the window's findable surface
            return ContentsSnapshotLimiter(rate.time_ms)
        if grouped:
            return GroupedSnapshotLimiter(
                layout, rate.time_ms, dtypes.config.snapshot_group_capacity,
                group_capacity)
        return SnapshotLimiter(layout, rate.time_ms)
    if rate.event_count is not None:
        n = rate.event_count
        kind = rate.type.value  # all | first | last
        if kind == "first":
            return EventOrdinalLimiter(n, "first")
        if kind == "last":
            return EventOrdinalLimiter(n, "last")
        return BufferedLimiter(layout, out_width, n_events=n)
    # time-driven
    t = rate.time_ms
    kind = rate.type.value
    if kind == "first":
        return TimeFirstLimiter(t)
    return BufferedLimiter(layout, out_width, time_ms=t, which=kind)
