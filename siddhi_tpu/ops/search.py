"""Branchless unrolled binary search.

XLA lowers `jnp.searchsorted` to a `while` HLO whose per-iteration dispatch
dominated sliding-window steps on TPU (profiled at ~50% of step time: the
loop body runs as 2 small fusions x log2(N) iterations with loop overhead
between each). A static unroll of the same log2(N) halving steps compiles to
straight-line vector code XLA fuses into neighbouring ops.

Semantics match `jnp.searchsorted(a, v, side=...)` for a sorted 1-D `a`,
returning int32 (positions are lane indices; int64 lane math is emulated on
TPU — see ops/windows.py)."""

from __future__ import annotations

import math

import jax.numpy as jnp


def searchsorted32(a, v, side: str = "left"):
    """Positions where `v` would insert into sorted `a`, as int32.

    a: sorted [N]; v: any shape. side='left' counts elements < v,
    side='right' counts elements <= v — same as jnp.searchsorted.
    """
    N = a.shape[0]
    pos = jnp.zeros(jnp.shape(v), jnp.int32)
    if N == 0:
        return pos
    bits = max(1, math.ceil(math.log2(N + 1)))
    for shift in range(bits - 1, -1, -1):
        step = jnp.int32(1 << shift)
        cand = pos + step
        probe = a[jnp.clip(cand - 1, 0, N - 1)]
        ok = probe < v if side == "left" else probe <= v
        pos = jnp.where((cand <= N) & ok, cand, pos)
    return pos
