"""Branchless unrolled binary search.

XLA lowers `jnp.searchsorted` to a `while` HLO whose per-iteration dispatch
dominated sliding-window steps on TPU (profiled at ~50% of step time: the
loop body runs as 2 small fusions x log2(N) iterations with loop overhead
between each). A static unroll of the same log2(N) halving steps compiles to
straight-line vector code XLA fuses into neighbouring ops.

Semantics match `jnp.searchsorted(a, v, side=...)` for a sorted 1-D `a`,
returning int32 (positions are lane indices; int64 lane math is emulated on
TPU — see ops/windows.py)."""

from __future__ import annotations

import math

import jax.numpy as jnp


def stable_partition_order(live):
    """Permutation that stably moves live lanes to the front — two prefix
    sums + one scatter instead of a sort. XLA CPU's comparator sort is
    ~50x slower than its cumsum at the same width (74 ms vs 1.4 ms at 282k
    lanes, measured); on TPU the scatter form also beats bitonic argsort.
    Replaces the `argsort(~live, stable=True)` idiom everywhere."""
    n = live.shape[0]
    live_i = live.astype(jnp.int32)
    pos_live = jnp.cumsum(live_i) - 1
    n_live = jnp.sum(live_i)
    pos_dead = n_live + jnp.cumsum(1 - live_i) - 1
    dest = jnp.where(live, pos_live, pos_dead)
    iota = jnp.arange(n, dtype=jnp.int32)
    return jnp.zeros((n,), jnp.int32).at[dest].set(iota)


def _host_radix_argsort(a):
    import numpy as np
    out = np.empty(a.shape, dtype=np.int32)
    from .. import native as native_mod
    nat = native_mod.native
    if a.ndim == 1:
        if nat is not None and hasattr(nat, "radix_argsort"):
            nat.radix_argsort(np.ascontiguousarray(a), out)
        else:
            out[...] = np.argsort(a, kind="stable")
        return out
    flat = a.reshape(-1, a.shape[-1])
    oflat = out.reshape(-1, a.shape[-1])
    for i in range(flat.shape[0]):
        if nat is not None and hasattr(nat, "radix_argsort"):
            nat.radix_argsort(np.ascontiguousarray(flat[i]), oflat[i])
        else:
            oflat[i] = np.argsort(flat[i], kind="stable")
    return out


#: lane count below which the plain native argsort is used instead of the
#: packed single-key sort. Historical meaning (kept for the cost model and
#: the legacy-callback escape hatch): on CPU this was the width above which
#: the C radix argsort pure_callback won over XLA's comparator sort. A host
#: callback anywhere in a jitted program disables pjit's C++ fastpath for
#: EVERY call of that executable (jax `_get_fastpath_data` vetoes
#: host_callbacks), costing ~0.5-6 ms of python dispatch per step — so the
#: callback traded per-sort time for per-dispatch time. The packed-key sort
#: below keeps the asymptotic win on device with no callback.
_RADIX_SORT_MIN_LANES = 8192


def _radix_min_lanes() -> int:
    import os
    try:
        return int(os.environ.get("SIDDHI_RADIX_SORT_MIN", "")
                   or _RADIX_SORT_MIN_LANES)
    except ValueError:
        return _RADIX_SORT_MIN_LANES


def _legacy_callback_enabled() -> bool:
    """Deprecated escape hatch: SIDDHI_RADIX_CALLBACK=1 restores the old
    CPU `pure_callback` radix argsort (testing / A-B only — it vetoes
    pjit's fastpath and makes the step superstep-ineligible)."""
    import os
    return os.environ.get("SIDDHI_RADIX_CALLBACK", "").strip() == "1"


def stable_argsort_bounded(x):
    """Stable argsort of NON-NEGATIVE int32 keys, as int32 positions.

    Narrow batches: native `jnp.argsort(stable=True)`. Wide batches: pack
    `(key << 32) | lane` into one int64 word and run a SINGLE unstable
    single-operand `lax.sort` — the lane index in the low bits makes the
    order stable by construction and the low 32 bits of the sorted words
    ARE the argsort. One sort over one operand instead of argsort's
    internal (key, iota) co-sort, and — unlike the retired CPU radix
    `pure_callback` — it stays on device, so the compiled step keeps
    pjit's C++ fastpath and can ride inside a superstep `lax.scan`
    (core/superstep.py). Keys are bounded (< 2^31), so the shifted word
    never overflows int64. The deprecated callback path survives behind
    SIDDHI_RADIX_CALLBACK=1 for A/B tests only."""
    import jax
    from jax import lax, pure_callback

    def legacy_cpu_fn(v):
        return pure_callback(
            _host_radix_argsort,
            jax.ShapeDtypeStruct(v.shape, jnp.int32), v,
            vmap_method="broadcast_all")

    def default_fn(v):
        return jnp.argsort(v, axis=-1, stable=True).astype(jnp.int32)

    def packed_fn(v):
        lane = lax.broadcasted_iota(jnp.int64, v.shape, v.ndim - 1)
        packed = (v.astype(jnp.int64) << 32) | lane
        swords = lax.sort(packed, dimension=v.ndim - 1, is_stable=False)
        return (swords & jnp.int64(0xFFFFFFFF)).astype(jnp.int32)

    if x.shape[-1] < _radix_min_lanes():
        return default_fn(x)
    if _legacy_callback_enabled():
        return lax.platform_dependent(x, cpu=legacy_cpu_fn,
                                      default=default_fn)
    # int64 lane math is emulated on TPU — keep the native argsort there
    return lax.platform_dependent(x, cpu=packed_fn, default=default_fn)


def searchsorted32(a, v, side: str = "left"):
    """Positions where `v` would insert into sorted `a`, as int32.

    a: sorted [N]; v: any shape. side='left' counts elements < v,
    side='right' counts elements <= v — same as jnp.searchsorted.
    """
    N = a.shape[0]
    pos = jnp.zeros(jnp.shape(v), jnp.int32)
    if N == 0:
        return pos
    bits = max(1, math.ceil(math.log2(N + 1)))
    for shift in range(bits - 1, -1, -1):
        step = jnp.int32(1 << shift)
        cand = pos + step
        probe = a[jnp.clip(cand - 1, 0, N - 1)]
        ok = probe < v if side == "left" else probe <= v
        pos = jnp.where((cand <= N) & ok, cand, pos)
    return pos
