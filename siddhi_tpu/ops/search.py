"""Branchless unrolled binary search.

XLA lowers `jnp.searchsorted` to a `while` HLO whose per-iteration dispatch
dominated sliding-window steps on TPU (profiled at ~50% of step time: the
loop body runs as 2 small fusions x log2(N) iterations with loop overhead
between each). A static unroll of the same log2(N) halving steps compiles to
straight-line vector code XLA fuses into neighbouring ops.

Semantics match `jnp.searchsorted(a, v, side=...)` for a sorted 1-D `a`,
returning int32 (positions are lane indices; int64 lane math is emulated on
TPU — see ops/windows.py)."""

from __future__ import annotations

import math

import jax.numpy as jnp


def stable_partition_order(live):
    """Permutation that stably moves live lanes to the front — two prefix
    sums + one scatter instead of a sort. XLA CPU's comparator sort is
    ~50x slower than its cumsum at the same width (74 ms vs 1.4 ms at 282k
    lanes, measured); on TPU the scatter form also beats bitonic argsort.
    Replaces the `argsort(~live, stable=True)` idiom everywhere."""
    n = live.shape[0]
    live_i = live.astype(jnp.int32)
    pos_live = jnp.cumsum(live_i) - 1
    n_live = jnp.sum(live_i)
    pos_dead = n_live + jnp.cumsum(1 - live_i) - 1
    dest = jnp.where(live, pos_live, pos_dead)
    iota = jnp.arange(n, dtype=jnp.int32)
    return jnp.zeros((n,), jnp.int32).at[dest].set(iota)


def _host_radix_argsort(a):
    import numpy as np
    out = np.empty(a.shape, dtype=np.int32)
    from .. import native as native_mod
    nat = native_mod.native
    if a.ndim == 1:
        if nat is not None and hasattr(nat, "radix_argsort"):
            nat.radix_argsort(np.ascontiguousarray(a), out)
        else:
            out[...] = np.argsort(a, kind="stable")
        return out
    flat = a.reshape(-1, a.shape[-1])
    oflat = out.reshape(-1, a.shape[-1])
    for i in range(flat.shape[0]):
        if nat is not None and hasattr(nat, "radix_argsort"):
            nat.radix_argsort(np.ascontiguousarray(flat[i]), oflat[i])
        else:
            oflat[i] = np.argsort(flat[i], kind="stable")
    return out


#: lane count below which CPU uses XLA's native sort instead of the radix
#: pure_callback. A host callback anywhere in a jitted program disables
#:  pjit's C++ fastpath for EVERY call of that executable (jax
#: `_get_fastpath_data` vetoes host_callbacks), costing ~0.5-6 ms of python
#: dispatch per step — far more than a small comparator sort. Measured on
#: this backend: native argsort 45 us @256 lanes / 2.5 ms @8192; radix
#: callback ~0.7 ms flat. Above the threshold the radix asymptotics win
#: (74 ms vs 4 ms at 262k lanes).
_RADIX_SORT_MIN_LANES = 8192


def _radix_min_lanes() -> int:
    import os
    try:
        return int(os.environ.get("SIDDHI_RADIX_SORT_MIN", "")
                   or _RADIX_SORT_MIN_LANES)
    except ValueError:
        return _RADIX_SORT_MIN_LANES


def stable_argsort_bounded(x):
    """Stable argsort of NON-NEGATIVE int32 keys, as int32 positions.

    TPU/other accelerators: native `jnp.argsort` (fast there). CPU backend,
    wide batches only: an LSD radix argsort in C reached via
    `jax.pure_callback` — XLA CPU's comparator sort runs ~260 ns/elem
    (74 ms at 282k lanes, measured) while the radix pass is ~10 ns/elem.
    Narrow batches stay on the native sort: the callback would knock the
    whole compiled step off pjit's C++ fastpath (see _RADIX_SORT_MIN_LANES)
    — which also matters for fused multi-query steps (core/shared.py),
    where one callback-bearing member would slow every co-resident query.
    The callback is batch-aware (trailing axis) so it stays vmappable."""
    import jax
    from jax import lax, pure_callback

    def cpu_fn(v):
        return pure_callback(
            _host_radix_argsort,
            jax.ShapeDtypeStruct(v.shape, jnp.int32), v,
            vmap_method="broadcast_all")

    def default_fn(v):
        return jnp.argsort(v, axis=-1, stable=True).astype(jnp.int32)

    if x.shape[-1] < _radix_min_lanes():
        return default_fn(x)
    return lax.platform_dependent(x, cpu=cpu_fn, default=default_fn)


def searchsorted32(a, v, side: str = "left"):
    """Positions where `v` would insert into sorted `a`, as int32.

    a: sorted [N]; v: any shape. side='left' counts elements < v,
    side='right' counts elements <= v — same as jnp.searchsorted.
    """
    N = a.shape[0]
    pos = jnp.zeros(jnp.shape(v), jnp.int32)
    if N == 0:
        return pos
    bits = max(1, math.ceil(math.log2(N + 1)))
    for shift in range(bits - 1, -1, -1):
        step = jnp.int32(1 << shift)
        cand = pos + step
        probe = a[jnp.clip(cand - 1, 0, N - 1)]
        ok = probe < v if side == "left" else probe <= v
        pos = jnp.where((cand <= N) & ok, cand, pos)
    return pos
