"""Expression AST → jitted JAX column functions.

Reference counterpart: core/util/parser/ExpressionParser.java:225 builds an
interpreter tree of monomorphic ExpressionExecutor objects that is walked per
event (virtual dispatch + boxing). Here the tree is *traced once*: compilation
returns a Python closure over columnar scopes which, evaluated inside the
query's jitted step function, fuses into a single XLA kernel — filters become
vectorized boolean masks over whole micro-batches (FilterProcessor.java:48-60's
hot loop disappears into the VPU).

Typing mirrors the reference's parse-time executor selection: every node gets a
static AttributeType; math promotes int<long<float<double
(core/executor/math/*); comparisons across numeric types promote before
comparing (core/executor/condition/compare/*).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.dtypes import NULL_CODE
from ..core.event import StreamCodec
from ..errors import SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..query_api.definition import AttributeType
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    In,
    IsNull,
    MathExpression,
    MathOp,
    Not,
    Or,
    Variable,
)


class Scope:
    """Column environment for one trace: maps (stream_ref, attr) -> array[B].

    For single-stream queries there is one default frame; joins/patterns add
    one frame per stream reference (the analogue of the reference's
    MetaStateEvent position addressing, StreamEvent.getAttribute:131).
    Also carries the batch timestamp vector and per-frame validity.
    """

    def __init__(self) -> None:
        self.frames: dict[str, dict[str, jax.Array]] = {}
        self.valids: dict[str, jax.Array] = {}
        self.ts: dict[str, jax.Array] = {}
        self.default_frame: Optional[str] = None
        #: extra context (e.g. tables for `in` lookups)
        self.extras: dict[str, object] = {}

    def add_frame(self, ref: str, cols: dict[str, jax.Array], ts: jax.Array,
                  valid: jax.Array, default: bool = False) -> None:
        self.frames[ref] = cols
        self.ts[ref] = ts
        self.valids[ref] = valid
        if default or self.default_frame is None:
            self.default_frame = ref

    def col(self, ref: Optional[str], attr: str) -> jax.Array:
        if ref is not None:
            return self.frames[ref][attr]
        # unqualified: search default frame first, then unique match
        if self.default_frame and attr in self.frames[self.default_frame]:
            return self.frames[self.default_frame][attr]
        hits = [f for f in self.frames.values() if attr in f]
        if len(hits) != 1:
            raise KeyError(attr)
        return hits[0][attr]


@dataclass
class CompiledExpr:
    """A typed, traceable column function."""

    fn: Callable[[Scope], jax.Array]
    type: AttributeType

    def __call__(self, scope: Scope) -> jax.Array:
        return self.fn(scope)


@dataclass
class ScalarFunction:
    """SPI for scalar function extensions (reference:
    core/executor/function/FunctionExecutor.java). `make(arg_types)` returns
    (jax_fn, return_type); jax_fn maps arg arrays -> result array and must be
    traceable (no Python control flow on values)."""

    make: Callable[[tuple[AttributeType, ...]], tuple[Callable, AttributeType]]


class TypeResolver:
    """Resolves Variable -> (frame_ref, attr, AttributeType). Built by the query
    planner from the FROM-clause stream definitions."""

    def __init__(self, frames: dict[str, dict[str, AttributeType]],
                 default_frame: Optional[str] = None,
                 codecs: Optional[dict[str, StreamCodec]] = None,
                 set_projections: Optional[dict[str, set]] = None) -> None:
        self.frames = frames
        self.default_frame = default_frame or (next(iter(frames)) if frames else None)
        self.codecs = codecs or {}
        #: frame_ref -> attr names carrying a forwarded unionSet SET-SIZE
        #: projection (Attribute.set_projection provenance) — the only
        #: columns sizeOfSet() accepts downstream
        self.set_projections = set_projections or {}

    def is_set_projection(self, frame_ref: Optional[str], attr: str) -> bool:
        ref = frame_ref or self.default_frame
        return attr in self.set_projections.get(ref, ())

    def resolve(self, v: Variable) -> tuple[Optional[str], str, AttributeType]:
        if v.stream_id is not None:
            frame = self.frames.get(v.stream_id)
            if frame is None or v.attribute not in frame:
                raise SiddhiAppCreationError(
                    f"unknown attribute {v.stream_id}.{v.attribute}")
            return v.stream_id, v.attribute, frame[v.attribute]
        if self.default_frame and v.attribute in self.frames[self.default_frame]:
            return None, v.attribute, self.frames[self.default_frame][v.attribute]
        hits = [(ref, f[v.attribute]) for ref, f in self.frames.items() if v.attribute in f]
        if len(hits) == 1:
            return hits[0][0], v.attribute, hits[0][1]
        raise SiddhiAppCreationError(
            f"attribute {v.attribute!r} is {'ambiguous' if hits else 'undefined'}")

    def string_code(self, frame_ref: Optional[str], attr: str, s: str) -> int:
        """Intern a string constant against the codec of the frame that owns
        `attr` so device comparison is code equality."""
        ref = frame_ref or self.default_frame
        codec = self.codecs.get(ref)
        if codec is None or attr not in codec.string_tables:
            raise SiddhiAppCreationError(
                f"no string table for {ref}.{attr}; string comparison unsupported here")
        return codec.string_tables[attr].encode(s)


_CONST_TYPES = {
    "int": AttributeType.INT, "long": AttributeType.LONG,
    "float": AttributeType.FLOAT, "double": AttributeType.DOUBLE,
    "bool": AttributeType.BOOL, "string": AttributeType.STRING,
    "time": AttributeType.LONG,
}


def compile_expression(
    expr: Expression,
    resolver: TypeResolver,
    registry: Registry,
) -> CompiledExpr:
    """Recursively compile an AST node into a CompiledExpr."""

    if isinstance(expr, Constant):
        t = _CONST_TYPES[expr.type_name]
        if t == AttributeType.STRING:
            # bare string constant with no comparison context — return as host
            # string; comparisons special-case this (see _compile_compare).
            return CompiledExpr(lambda s, v=expr.value: v, t)
        dt = dtypes.device_dtype(t)
        val = expr.value
        return CompiledExpr(lambda s, v=val, d=dt: jnp.asarray(v, dtype=d), t)

    if isinstance(expr, Variable):
        ref, attr, t = resolver.resolve(expr)
        return CompiledExpr(lambda s, r=ref, a=attr: s.col(r, a), t)

    if isinstance(expr, MathExpression):
        return _compile_math(expr, resolver, registry)

    if isinstance(expr, Compare):
        return _compile_compare(expr, resolver, registry)

    if isinstance(expr, And):
        l = compile_expression(expr.left, resolver, registry)
        r = compile_expression(expr.right, resolver, registry)
        _require_bool(l, r)
        return CompiledExpr(lambda s: l(s) & r(s), AttributeType.BOOL)

    if isinstance(expr, Or):
        l = compile_expression(expr.left, resolver, registry)
        r = compile_expression(expr.right, resolver, registry)
        _require_bool(l, r)
        return CompiledExpr(lambda s: l(s) | r(s), AttributeType.BOOL)

    if isinstance(expr, Not):
        e = compile_expression(expr.expression, resolver, registry)
        _require_bool(e)
        return CompiledExpr(lambda s: ~e(s), AttributeType.BOOL)

    if isinstance(expr, IsNull):
        return _compile_is_null(expr, resolver, registry)

    if isinstance(expr, In):
        return _compile_in(expr, resolver, registry)

    if isinstance(expr, AttributeFunction):
        return _compile_function(expr, resolver, registry)

    raise SiddhiAppCreationError(f"cannot compile expression node {type(expr).__name__}")


def _require_bool(*exprs: CompiledExpr) -> None:
    for e in exprs:
        if e.type != AttributeType.BOOL:
            raise SiddhiAppCreationError(
                f"logical operator requires bool operands, got {e.type}")


def _compile_math(expr: MathExpression, resolver: TypeResolver, registry: Registry) -> CompiledExpr:
    l = compile_expression(expr.left, resolver, registry)
    r = compile_expression(expr.right, resolver, registry)
    out_t = dtypes.promote(l.type, r.type)
    if expr.op == MathOp.DIVIDE:
        # Java semantics (reference DivideExpressionExecutor*): int/long pairs
        # use integer division truncating toward zero (lax.div); div-by-zero
        # lanes are zeroed instead of trapping (they are masked out upstream).
        if out_t in (AttributeType.INT, AttributeType.LONG):
            return CompiledExpr(
                lambda s: jnp.where(r(s) != 0, jax.lax.div(l(s), r(s)), jnp.zeros_like(l(s))),
                out_t)
        return CompiledExpr(lambda s: _cast(l(s), out_t) / _cast(r(s), out_t), out_t)
    if expr.op == MathOp.MOD:
        if out_t in (AttributeType.INT, AttributeType.LONG):
            # Java % truncates toward zero (lax.rem), unlike jnp.mod (floor).
            return CompiledExpr(lambda s: jnp.where(r(s) != 0, jax.lax.rem(l(s), r(s)), jnp.zeros_like(l(s))), out_t)
        return CompiledExpr(lambda s: jax.lax.rem(_cast(l(s), out_t), _cast(r(s), out_t)), out_t)
    ops = {MathOp.ADD: jnp.add, MathOp.SUBTRACT: jnp.subtract, MathOp.MULTIPLY: jnp.multiply}
    op = ops[expr.op]
    return CompiledExpr(lambda s: op(_cast(l(s), out_t), _cast(r(s), out_t)), out_t)


def _cast(arr: jax.Array, t: AttributeType) -> jax.Array:
    return arr.astype(dtypes.device_dtype(t))


_CMP = {
    CompareOp.EQUAL: jnp.equal,
    CompareOp.NOT_EQUAL: jnp.not_equal,
    CompareOp.GREATER_THAN: jnp.greater,
    CompareOp.GREATER_THAN_EQUAL: jnp.greater_equal,
    CompareOp.LESS_THAN: jnp.less,
    CompareOp.LESS_THAN_EQUAL: jnp.less_equal,
}


def _compile_compare(expr: Compare, resolver: TypeResolver, registry: Registry) -> CompiledExpr:
    # String comparisons: intern the constant side into the variable side's
    # string table so the device compares int32 codes.
    lc, rc = expr.left, expr.right
    l_str_const = isinstance(lc, Constant) and lc.type_name == "string"
    r_str_const = isinstance(rc, Constant) and rc.type_name == "string"
    if l_str_const or r_str_const:
        var_side, const_side = (rc, lc) if l_str_const else (lc, rc)
        if not isinstance(var_side, Variable):
            raise SiddhiAppCreationError(
                "string comparison requires an attribute on one side")
        ref, attr, t = resolver.resolve(var_side)
        if t != AttributeType.STRING:
            raise SiddhiAppCreationError(f"cannot compare {t} with string constant")
        if expr.op not in (CompareOp.EQUAL, CompareOp.NOT_EQUAL):
            raise SiddhiAppCreationError(
                "string constants support only ==/!= on device")
        code = resolver.string_code(ref, attr, const_side.value)
        op = _CMP[expr.op]
        return CompiledExpr(lambda s, c=code: op(s.col(ref, attr), jnp.int32(c)),
                            AttributeType.BOOL)

    l = compile_expression(lc, resolver, registry)
    r = compile_expression(rc, resolver, registry)
    op = _CMP[expr.op]
    if l.type == AttributeType.STRING and r.type == AttributeType.STRING:
        # code equality is only sound for == / != (codes are not ordered)
        if expr.op not in (CompareOp.EQUAL, CompareOp.NOT_EQUAL):
            raise SiddhiAppCreationError("string ordering comparisons unsupported on device")
        return CompiledExpr(lambda s: op(l(s), r(s)), AttributeType.BOOL)
    if l.type == AttributeType.BOOL or r.type == AttributeType.BOOL:
        if l.type != r.type:
            raise SiddhiAppCreationError(f"cannot compare {l.type} with {r.type}")
        return CompiledExpr(lambda s: op(l(s), r(s)), AttributeType.BOOL)
    out_t = dtypes.promote(l.type, r.type)
    return CompiledExpr(lambda s: op(_cast(l(s), out_t), _cast(r(s), out_t)),
                        AttributeType.BOOL)


def _compile_is_null(expr: IsNull, resolver: TypeResolver, registry: Registry) -> CompiledExpr:
    if expr.stream_id is not None:
        # `e2 is null` — pattern-stream nullness: tests the frame validity mask.
        sid = expr.stream_id
        return CompiledExpr(lambda s: ~s.valids[sid], AttributeType.BOOL)
    inner = expr.expression
    if isinstance(inner, Variable):
        ref, attr, t = resolver.resolve(inner)
        if t == AttributeType.STRING:
            return CompiledExpr(
                lambda s: s.col(ref, attr) == jnp.int32(NULL_CODE), AttributeType.BOOL)
        # numeric columns have no per-attribute null on device (see dtypes.py);
        # null only arises from invalid frames (outer joins / absent patterns).
        if ref is not None:
            return CompiledExpr(lambda s: ~s.valids[ref] if ref in s.valids
                                else jnp.zeros_like(s.col(ref, attr), dtype=bool),
                                AttributeType.BOOL)
        return CompiledExpr(
            lambda s: jnp.zeros(s.col(ref, attr).shape, dtype=bool), AttributeType.BOOL)
    e = compile_expression(inner, resolver, registry)
    return CompiledExpr(lambda s: jnp.zeros(jnp.shape(e(s)), dtype=bool), AttributeType.BOOL)


def _compile_in(expr: In, resolver: TypeResolver, registry: Registry) -> CompiledExpr:
    # Planned by the query runtime: it registers a membership probe closure under
    # scope.extras['in:<table>'] that maps the compiled condition over the table.
    inner = compile_expression(expr.expression, resolver, registry) if expr.expression else None
    source = expr.source_id

    # index-aware plan (reference: CollectionExpressionParser choosing a
    # CompareCollectionExecutor over ExhaustiveCollectionExecutor): a single
    # `T.attr == <stream expr>` equality probes the table's sorted index
    eq_plan = None
    e = expr.expression
    if isinstance(e, Compare) and e.op == CompareOp.EQUAL:
        for tside, sside in ((e.left, e.right), (e.right, e.left)):
            if not (isinstance(tside, Variable) and tside.stream_id == source):
                continue
            if _references_frame(sside, source, resolver):
                continue
            if isinstance(sside, Constant) and sside.type_name == "string":
                # intern against the TABLE attribute's string table so the
                # probe compares int32 codes (same app-global space)
                try:
                    code = resolver.string_code(source, tside.attribute,
                                                sside.value)
                except SiddhiAppCreationError:
                    break
                sc = CompiledExpr(
                    lambda s, c=code: jnp.full(
                        s.ts[s.default_frame].shape, c, jnp.int32),
                    AttributeType.STRING)
            else:
                try:
                    sc = compile_expression(sside, resolver, registry)
                except SiddhiAppCreationError:
                    break
            # type divergence guard: the sorted-copy probe compares in the
            # TABLE column's dtype; mixed-type compares (int column vs
            # double stream value) must keep the exhaustive path, which
            # promotes both sides
            try:
                _, _, t_type = resolver.resolve(tside)
            except Exception:
                break
            if sc.type != t_type:
                break
            eq_plan = (tside.attribute, sc)
            break

    def fn(s: Scope):
        probe = s.extras.get(f"in:{source}")
        if probe is None:
            raise SiddhiAppCreationError(
                f"`in {source}` used outside a table-aware context")
        return probe(s, inner, eq_plan)

    return CompiledExpr(fn, AttributeType.BOOL)


def _references_frame(e: Expression, frame: str, resolver: TypeResolver) -> bool:
    if isinstance(e, Variable):
        if e.stream_id is not None:
            return e.stream_id == frame
        # an unqualified variable may resolve to the table frame
        try:
            ref, _, _ = resolver.resolve(e)
        except Exception:
            return True  # unresolvable: be conservative, decline the plan
        return ref == frame
    for attr in ("left", "right", "expression"):
        sub = getattr(e, attr, None)
        if isinstance(sub, Expression) and _references_frame(sub, frame, resolver):
            return True
    for p_ in getattr(e, "parameters", ()) or ():
        if isinstance(p_, Expression) and _references_frame(p_, frame, resolver):
            return True
    return False


def _compile_function(expr: AttributeFunction, resolver: TypeResolver,
                      registry: Registry) -> CompiledExpr:
    # Planner-resolved built-ins (reference: EventTimestampFunctionExecutor,
    # CurrentTimeMillisFunctionExecutor): these read batch context, not columns.
    if not expr.namespace and expr.name == "eventTimestamp":
        if expr.parameters:
            sid = expr.parameters[0]
            if isinstance(sid, Variable):
                return CompiledExpr(lambda s, r=sid.attribute: s.ts[r], AttributeType.LONG)
        return CompiledExpr(lambda s: s.ts[s.default_frame], AttributeType.LONG)
    if not expr.namespace and expr.name == "currentTimeMillis":
        return CompiledExpr(
            lambda s: jnp.broadcast_to(s.extras["now"], s.ts[s.default_frame].shape),
            AttributeType.LONG)
    # sizeOfSet over a FORWARDED raw-unionSet column: the lane already
    # carries the exact distinct count (LONG set-size projection). Accepted
    # ONLY with unionSet provenance (Attribute.set_projection riding the
    # producing query's output definition / table marker) — an ordinary
    # LONG column raises instead of silently forwarding its value
    # (ADVICE r5; sizeOfSet(unionSet(...)) in ONE query rewrites to
    # distinctCount in the selector and never reaches here).
    if (not expr.namespace and expr.name == "sizeOfSet"
            and len(expr.parameters) == 1
            and isinstance(expr.parameters[0], Variable)):
        v = expr.parameters[0]
        ref, attr, t = resolver.resolve(v)
        if t == AttributeType.LONG and resolver.is_set_projection(ref, attr):
            dt = dtypes.device_dtype(AttributeType.LONG)
            return CompiledExpr(
                lambda s, r=ref, a=attr, d=dt: s.col(r, a).astype(d),
                AttributeType.LONG)
        raise SiddhiAppCreationError(
            f"sizeOfSet({v.attribute}): the column does not carry a "
            "unionSet set-size projection — only a forwarded `select "
            "unionSet(x) as s` output (auto-defined stream or insert-into "
            "table) is readable by sizeOfSet downstream; an ordinary "
            f"{t.value} attribute would silently forward its value")

    args = tuple(compile_expression(p, resolver, registry) for p in expr.parameters)
    impl = registry.lookup(ExtensionKind.FUNCTION, expr.namespace, expr.name)
    if impl is None:
        raise SiddhiAppCreationError(
            f"no function extension {expr.full_name!r} "
            f"(aggregators are valid only in SELECT)")
    assert isinstance(impl, ScalarFunction)
    jax_fn, ret_t = impl.make(tuple(a.type for a in args))
    return CompiledExpr(lambda s: jax_fn(*(a(s) for a in args)), ret_t)
