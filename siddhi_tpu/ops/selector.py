"""Compiled SELECT engine (reference: core/query/selector/QuerySelector.java:44).

Consumes window chunks (typed lanes CURRENT/EXPIRED/RESET) and produces an
output EventBatch of projected attributes, reproducing per-event semantics:

- aggregator components update per-key via grouped scans with signed deltas
  (CURRENT=+1, EXPIRED=-1, RESET=epoch bump), emitting the post-update value on
  every lane — exactly QuerySelector.processGroupBy's per-event emission;
- HAVING filters output lanes (QuerySelector.java:228);
- ORDER BY / LIMIT / OFFSET apply per chunk (QuerySelector.java:230-235).

Aggregator calls may be nested inside arbitrary expressions
(`sum(price)/count()`); they are rewritten to references into a synthetic
`__agg__` frame evaluated first.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.event import EventBatch, EventType
from ..errors import SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..query_api.definition import AttributeType
from ..query_api.execution import OrderByOrder, Selector
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    Expression,
    In,
    IsNull,
    MathExpression,
    Not,
    Or,
    Variable,
)
from .aggregators import AggregatorFactory, AggregatorSpec
from .expr_compile import CompiledExpr, Scope, TypeResolver, compile_expression
from .groupby import (
    GroupState,
    KeyTable,
    grouped_scan,
    grouped_scan_fused,
    hash_columns,
    init_group_state,
    init_key_table,
    key_lookup_or_insert,
    ungrouped_scan,
    ungrouped_scan_fused,
)

AGG_FRAME = "__agg__"
#: pseudo-column carrying each output lane's group slot (grouped snapshot
#: rate limiting); never part of the output schema
GROUP_SLOT_COL = "__slot__"


def _rewrite_set_idioms(expr: Expression) -> Expression:
    """`sizeOfSet(unionSet(createSet(x)))` (reference:
    UnionSetAttributeAggregatorExecutor + CreateSet/SizeOfSet function
    executors) compiles to an EXACT distinct count on device — the set is
    never materialized. Raw set emission stays host-opaque and is rejected
    at plan time with guidance (see the unionSet registry entry)."""
    if isinstance(expr, AttributeFunction):
        if not expr.namespace and expr.name == "sizeOfSet" and expr.parameters:
            inner = expr.parameters[0]
            if (isinstance(inner, AttributeFunction) and not inner.namespace
                    and inner.name == "unionSet" and inner.parameters):
                arg = inner.parameters[0]
                if (isinstance(arg, AttributeFunction) and not arg.namespace
                        and arg.name == "createSet" and arg.parameters):
                    arg = arg.parameters[0]
                return AttributeFunction("", "distinctCount",
                                         (_rewrite_set_idioms(arg),))
        return AttributeFunction(
            expr.namespace, expr.name,
            tuple(_rewrite_set_idioms(p) for p in expr.parameters))
    for field in ("left", "right", "expression"):
        sub = getattr(expr, field, None)
        if isinstance(sub, Expression):
            expr = dataclasses.replace(
                expr, **{field: _rewrite_set_idioms(sub)})
    return expr


def _rewrite_aggregators(expr: Expression, registry: Registry, found: list):
    """Replace aggregator AttributeFunction nodes with Variables into the
    __agg__ frame; collect (name, node) into `found`. Mirrors the reference's
    aggregator detection at parse time (ExpressionParser.java:462)."""
    if isinstance(expr, AttributeFunction):
        impl = registry.lookup(ExtensionKind.AGGREGATOR, expr.namespace, expr.name)
        if impl is not None:
            slot_name = f"agg{len(found)}"
            found.append((slot_name, expr))
            return Variable(slot_name, stream_id=AGG_FRAME)
        new_params = tuple(_rewrite_aggregators(p, registry, found)
                           for p in expr.parameters)
        return AttributeFunction(expr.namespace, expr.name, new_params)
    if isinstance(expr, MathExpression):
        return dataclasses.replace(
            expr,
            left=_rewrite_aggregators(expr.left, registry, found),
            right=_rewrite_aggregators(expr.right, registry, found))
    if isinstance(expr, Compare):
        return dataclasses.replace(
            expr,
            left=_rewrite_aggregators(expr.left, registry, found),
            right=_rewrite_aggregators(expr.right, registry, found))
    if isinstance(expr, (And, Or)):
        return dataclasses.replace(
            expr,
            left=_rewrite_aggregators(expr.left, registry, found),
            right=_rewrite_aggregators(expr.right, registry, found))
    if isinstance(expr, Not):
        return dataclasses.replace(
            expr, expression=_rewrite_aggregators(expr.expression, registry, found))
    return expr


@dataclass
class SelectorState:
    """Pytree of selector persistent state.

    `groups` holds, in agg-spec order: bare [K] value arrays for FUSED
    components (plain sum-op — they share `shared_epoch`), GroupState for
    monotone/forever components, and custom pytrees for custom scans."""

    groups: list
    key_table: Optional[KeyTable]
    epoch: jax.Array  # int32
    shared_epoch: Optional[jax.Array] = None  # int32[K] for fused components


jax.tree_util.register_dataclass(SelectorState)


class CompiledSelector:
    """Plans one Selector against an input frame layout."""

    def __init__(
        self,
        selector: Selector,
        resolver: TypeResolver,
        registry: Registry,
        group_capacity: int,
        chunk_frame: str,
        select_all_attrs: Optional[list[tuple[str, AttributeType]]] = None,
        emit_final_per_group: bool = False,
        sliding_window: bool = False,
    ):
        self.registry = registry
        self.group_capacity = group_capacity
        self.chunk_frame = chunk_frame
        self.selector = selector
        #: on-demand (pull) mode: emit one lane per group — the final
        #: aggregate — instead of per-event running values (reference:
        #: FindOnDemandQueryRuntime returns one row per group)
        self.emit_final_per_group = emit_final_per_group
        #: set by the runtime before tracing when a grouped snapshot limiter
        #: needs per-lane group slots (GROUP_SLOT_COL)
        self.expose_group_slot = False

        # --- select list: rewrite aggregators, compile expressions ---
        agg_nodes: list[tuple[str, AttributeFunction]] = []
        attrs = selector.attributes
        if not attrs:
            # select * — project every input attribute
            if select_all_attrs is None:
                raise SiddhiAppCreationError("select * needs input attribute list")
            from ..query_api.execution import OutputAttribute
            attrs = tuple(OutputAttribute(n, Variable(n)) for n, _ in select_all_attrs)
        #: raw-set emission (reference:
        #: UnionSetAttributeAggregatorExecutor.java:71 returns the live Set
        #: object): `select unionSet(x) as s` compiles the LIVE-MULTISET
        #: tracking to an exact distinctCount on device; the query runtime
        #: materializes the set HOST-SIDE at the callback boundary from the
        #: per-code pair table. out name -> __agg__ slot (filled below).
        self.host_set_slots: dict[str, str] = {}
        pre = []
        for i, a in enumerate(attrs):
            e = _rewrite_set_idioms(a.expression)
            if (isinstance(e, AttributeFunction) and not e.namespace
                    and e.name == "unionSet" and e.parameters):
                arg = e.parameters[0]
                if (isinstance(arg, AttributeFunction) and not arg.namespace
                        and arg.name == "createSet" and arg.parameters):
                    arg = arg.parameters[0]
                if a.rename is None:
                    raise SiddhiAppCreationError(
                        "raw unionSet(...) output needs an `as` name")
                if selector.group_by:
                    raise SiddhiAppCreationError(
                        "raw unionSet(...) emission is ungrouped-only on "
                        "this engine (use sizeOfSet(unionSet(...)) for "
                        "grouped counts)")
                if compile_expression(arg, resolver,
                                      registry).type != AttributeType.STRING:
                    raise SiddhiAppCreationError(
                        "raw unionSet(...) emission needs a STRING argument "
                        "(host materialization reads the dictionary-code "
                        "table); use sizeOfSet(unionSet(...)) for counts "
                        "over other types")
                self.host_set_slots[a.rename] = ""  # agg slot filled below
                e = AttributeFunction("", "distinctCount", (arg,))
            pre.append((a.rename, e))
        rewritten = [(name, _rewrite_aggregators(e, registry, agg_nodes))
                     for name, e in pre]
        for name, re_ in rewritten:
            if name in self.host_set_slots:
                assert isinstance(re_, Variable)
                self.host_set_slots[name] = re_.attribute
        #: output slots whose value is generated host-side per event at the
        #: host boundary (UUID — reference UUIDFunctionExecutor); device
        #: lanes carry a placeholder code
        self.host_uuid_slots: list[str] = []
        for i, (name, e) in enumerate(rewritten):
            if (isinstance(e, AttributeFunction) and not e.namespace
                    and e.name == "UUID"):
                self.host_uuid_slots.append(name or f"UUID{i}")

        # --- aggregator specs ---
        self.agg_specs: list[tuple[str, AggregatorSpec, list[CompiledExpr]]] = []
        #: sliding-window true extrema: (slot, 'min'|'max', arg exprs) — the
        #: query runtime computes these as range queries over the window's
        #: arrival-order sequence (reference: Min/MaxAttributeAggregator
        #: processRemove) and injects per-lane values via scope extras
        self.extrema_plan: list[tuple[str, str, list[CompiledExpr]]] = []
        for slot_name, node in agg_nodes:
            factory = registry.require(ExtensionKind.AGGREGATOR, node.namespace, node.name)
            assert isinstance(factory, AggregatorFactory)
            args = [compile_expression(p, resolver, registry) for p in node.parameters]
            spec = factory.make(tuple(a.type for a in args))
            if sliding_window and spec.extrema_op is not None:
                self.extrema_plan.append((slot_name, spec.extrema_op, args))
            self.agg_specs.append((slot_name, spec, args))
        self._extrema_slots = {s for s, _, _ in self.extrema_plan}
        self.has_aggregators = bool(self.agg_specs)

        # grouped extrema need the group hash of both ring rows and chunk
        # lanes (ops/extrema.grouped_sliding_extrema_lanes); defined here so
        # ring-side and lane-side hashing can never diverge
        if self.extrema_plan and selector.group_by:
            gvars = [resolver.resolve(v) for v in selector.group_by]

            def group_hash(scope):
                return hash_columns(
                    [scope.col(ref, attr) for ref, attr, _ in gvars])

            self.extrema_group_hash = group_hash
        else:
            self.extrema_group_hash = None

        # --- resolver extended with the __agg__ frame ---
        frames = dict(resolver.frames)
        frames[AGG_FRAME] = {slot: spec.return_type
                             for slot, spec, _ in self.agg_specs}
        self.resolver = TypeResolver(frames, resolver.default_frame,
                                     resolver.codecs,
                                     resolver.set_projections)

        self.out_exprs: list[tuple[str, CompiledExpr]] = []
        for name, e in rewritten:
            if name in self.host_uuid_slots:
                # placeholder string code; the runtime substitutes uuid4()
                # per event at the host boundary
                self.out_exprs.append((name, CompiledExpr(
                    lambda s: jnp.zeros(
                        s.ts[s.default_frame].shape, jnp.int32),
                    AttributeType.STRING)))
            else:
                self.out_exprs.append(
                    (name, compile_expression(e, self.resolver, registry)))
        self.out_types: dict[str, AttributeType] = {
            name: ce.type for name, ce in self.out_exprs}
        for name in self.host_set_slots:
            # the device lane carries the EXACT distinct count: downstream
            # consumers (insert into T, chained queries) receive the
            # set-size projection as LONG — `sizeOfSet(T.s)` reads it
            # directly (reference forwards the live Set object,
            # UnionSetAttributeAggregatorExecutor.java:71; the size-at-
            # emission projection is the documented divergence,
            # docs/PARITY.md). Query callbacks still substitute the
            # MATERIALIZED host set at the boundary (union_set_values)
            self.out_types[name] = AttributeType.LONG

        # --- group-by key plan ---
        self.group_by = selector.group_by
        self.group_vars = [resolver.resolve(v) for v in selector.group_by]
        self.use_string_code = (
            len(self.group_vars) == 1 and self.group_vars[0][2] == AttributeType.STRING)
        self.needs_key_table = bool(self.group_vars) and not self.use_string_code

        # --- having / order by compiled against the output frame ---
        out_frames = dict(frames)
        out_frames["__out__"] = dict(self.out_types)
        out_resolver = TypeResolver(out_frames, "__out__", resolver.codecs,
                                    resolver.set_projections)
        self.having = (compile_expression(selector.having, out_resolver, registry)
                       if selector.having is not None else None)
        self.order_by = [(out_resolver.resolve(ob.variable), ob.order)
                         for ob in selector.order_by]
        self.limit = selector.limit
        self.offset = selector.offset

    # ------------------------------------------------------------------ state

    def init_state(self) -> SelectorState:
        groups = []
        K = self.group_capacity if self.group_vars else 1
        any_fused = False
        for slot_name, spec, _ in self.agg_specs:
            if slot_name in self._extrema_slots:
                continue  # runtime-computed; no device state
            if spec.custom_scan is not None:
                groups.append(spec.init_custom(
                    self.group_capacity, grouped=bool(self.group_vars)))
                continue
            for comp in spec.components:
                if (comp.op == "sum" and not comp.ignore_removal
                        and not comp.ignore_reset):
                    # fused components: bare values array, shared epoch table
                    groups.append(jnp.zeros((K,), dtype=comp.dtype))
                    any_fused = True
                else:
                    groups.append(init_group_state(K, comp.dtype))
        return SelectorState(
            groups=groups,
            key_table=init_key_table(K) if self.needs_key_table else None,
            epoch=jnp.int32(0),
            shared_epoch=jnp.zeros((K,), jnp.int32) if any_fused else None,
        )

    def union_set_values(self, sstate: "SelectorState", out_name: str,
                         string_table) -> set:
        """Materialize the LIVE value set behind a raw-unionSet output slot
        (ungrouped string fast path: per-code pair counts). One batched
        device fetch; codes decode through the app-global string table."""
        agg_slot = self.host_set_slots[out_name]
        off = 0
        state = None
        for slot_name, spec, _ in self.agg_specs:
            if slot_name in self._extrema_slots:
                continue
            if slot_name == agg_slot:
                state = sstate.groups[off]
                break
            off += 1 if spec.custom_scan is not None else len(spec.components)
        assert state is not None, f"no state for set slot {out_name!r}"
        pair_counts = state[0]  # (pair GroupState[P], distinct GroupState[1])
        vals, ep, cur = jax.device_get(
            (pair_counts.values, pair_counts.epoch, sstate.epoch))
        import numpy as np
        live = np.nonzero((ep == cur) & (vals > 0))[0]
        return {string_table.decode(int(c)) for c in live}

    # ------------------------------------------------------------------- step

    def step(self, state: SelectorState, chunk: EventBatch,
             scope: Scope) -> tuple[SelectorState, EventBatch]:
        L = chunk.capacity
        valid = chunk.valid
        types = chunk.types
        is_current = types == EventType.CURRENT
        is_expired = types == EventType.EXPIRED
        is_reset = valid & (types == EventType.RESET)
        data_valid = valid & (is_current | is_expired)

        new_key_table = state.key_table
        if self.group_vars:
            if self.use_string_code:
                ref, attr, _ = self.group_vars[0]
                slots = scope.col(ref, attr)
            else:
                key_cols = [scope.col(ref, attr) for ref, attr, _ in self.group_vars]
                hashed = hash_columns(key_cols)
                new_key_table, slots, kres = key_lookup_or_insert(
                    state.key_table, hashed, data_valid)
                # unresolved lanes (key table exhausted) must not alias
                # group 0: sentinel slots sort out of every segment scan
                # (monitored truncation via the table's miss counter)
                slots = jnp.where(kres, slots, jnp.int32(self.group_capacity))
        else:
            slots = jnp.zeros((L,), jnp.int32)

        sign = jnp.where(is_expired, -1, 1).astype(jnp.int32)

        # --- run aggregator components ---
        # plain sum-op components (sum/count/avg/stdDev parts) fuse into ONE
        # scan sharing one epoch table; monotone/forever/custom run separately
        new_groups = list(state.groups)
        gi = 0
        results: dict[int, jax.Array] = {}
        pending: list[tuple[str, AggregatorSpec, list[int]]] = []
        fused_idx: list[int] = []
        fused_vals: list = []
        fused_deltas: list = []
        any_reset = is_reset
        no_reset = jnp.zeros((L,), bool)
        extrema_values: dict[str, jax.Array] = {}
        for slot_name, spec, args in self.agg_specs:
            if slot_name in self._extrema_slots:
                # per-lane window extrema computed by the query runtime
                # (range queries over the window's arrival-order sequence)
                extrema_values[slot_name] = scope.extras[
                    f"extrema:{slot_name}"]
                continue
            arg_vals = [a(scope) for a in args] if args else [None]
            if spec.custom_scan is not None:
                g, out_vals = spec.custom_scan(
                    state.groups[gi], slots.astype(jnp.int32), arg_vals,
                    sign, data_valid, any_reset, state.epoch,
                    grouped=bool(self.group_vars))
                new_groups[gi] = g
                results[gi] = out_vals
                pending.append((slot_name, spec, [gi]))
                gi += 1
                continue
            comp_gis = []
            for comp in spec.components:
                deltas = comp.delta(arg_vals[0], sign)
                if (comp.op == "sum" and not comp.ignore_removal
                        and not comp.ignore_reset):
                    fused_idx.append(gi)
                    fused_vals.append(state.groups[gi])
                    fused_deltas.append(deltas)
                else:
                    lane_valid = data_valid if not comp.ignore_removal else (
                        valid & is_current)
                    resets = no_reset if comp.ignore_reset else any_reset
                    if self.group_vars:
                        g, out_vals = grouped_scan(
                            state.groups[gi], slots.astype(jnp.int32), deltas,
                            lane_valid, resets, state.epoch, op=comp.op)
                    else:
                        g, out_vals = ungrouped_scan(
                            state.groups[gi], deltas, lane_valid, resets,
                            state.epoch, op=comp.op)
                    new_groups[gi] = g
                    results[gi] = out_vals
                comp_gis.append(gi)
                gi += 1
            pending.append((slot_name, spec, comp_gis))

        shared_epoch = state.shared_epoch
        if fused_idx and self.group_vars:
            f_vals, shared_epoch, f_outs = grouped_scan_fused(
                fused_vals, state.shared_epoch, slots.astype(jnp.int32),
                fused_deltas, data_valid, any_reset, state.epoch)
            for i, g in zip(fused_idx, f_vals):
                new_groups[i] = g
            for i, o in zip(fused_idx, f_outs):
                results[i] = o
        elif fused_idx:
            f_vals, shared_epoch, f_outs = ungrouped_scan_fused(
                fused_vals, state.shared_epoch, fused_deltas, data_valid,
                any_reset, state.epoch)
            for i, g in zip(fused_idx, f_vals):
                new_groups[i] = g
            for i, o in zip(fused_idx, f_outs):
                results[i] = o

        agg_values: dict[str, jax.Array] = dict(extrema_values)
        for slot_name, spec, comp_gis in pending:
            if spec.custom_scan is not None:
                agg_values[slot_name] = results[comp_gis[0]]
            else:
                agg_values[slot_name] = spec.finalize(
                    [results[i] for i in comp_gis])

        # dtype-stable accumulate: a bare jnp.sum promotes int32->int64
        # under x64, silently changing the state aval between the first and
        # second step — which retriggers a FULL ~seconds-long XLA recompile
        new_epoch = state.epoch + jnp.sum(
            is_reset.astype(jnp.int32), dtype=state.epoch.dtype)

        # --- project output attributes ---
        if self.agg_specs:
            scope.frames[AGG_FRAME] = agg_values
            scope.valids[AGG_FRAME] = data_valid
            scope.ts[AGG_FRAME] = chunk.ts
        # constant-only projections (`select 1.0 as w`) trace to 0-d
        # scalars: broadcast to lane width so downstream decode/table
        # inserts see a proper column
        out_cols = {}
        for name, ce in self.out_exprs:
            v = ce(scope)
            if jnp.ndim(v) == 0:
                v = jnp.broadcast_to(v, chunk.ts.shape)
            out_cols[name] = v
        if self.expose_group_slot:
            # grouped snapshot limiters retain one row per group — ride the
            # per-lane group slot through ordering/limit as a pseudo-column
            out_cols[GROUP_SLOT_COL] = slots.astype(jnp.int32)

        out_valid = data_valid

        if self.emit_final_per_group and self.has_aggregators:
            # keep only the last lane of each group — its running aggregate is
            # the group's final value — BEFORE having, so HAVING judges the
            # final aggregate, not an intermediate running value
            idx = jnp.arange(L, dtype=jnp.int32)
            K = self.group_capacity if self.group_vars else 1
            last = jax.ops.segment_max(
                jnp.where(out_valid, idx, -1), slots.astype(jnp.int32),
                num_segments=K)
            out_valid = out_valid & (idx == last[slots.astype(jnp.int32)])

        # --- having on the output frame ---
        if self.having is not None or self.order_by:
            scope.frames["__out__"] = out_cols
            scope.valids["__out__"] = out_valid
            scope.ts["__out__"] = chunk.ts
        if self.having is not None:
            out_valid = out_valid & self.having(scope)

        out = EventBatch(ts=chunk.ts, cols=out_cols, valid=out_valid, types=types)

        # --- order by / offset / limit (per chunk, like the reference) ---
        if self.order_by:
            out = self._order_chunk(out)
        if self.offset is not None or self.limit is not None:
            out = self._limit_chunk(out)

        return SelectorState(new_groups, new_key_table, new_epoch,
                             shared_epoch), out

    def _order_chunk(self, out: EventBatch) -> EventBatch:
        keys = []
        for (ref, attr, _), order in reversed(self.order_by):
            col = out.cols[attr]
            if order == OrderByOrder.DESC:
                col = -col if jnp.issubdtype(col.dtype, jnp.number) else ~col
            keys.append(col)
        # push invalid lanes to the end, stable within
        perm = jnp.arange(out.capacity)
        for k in keys:
            k = jnp.where(out.valid[perm], k[perm].astype(jnp.float64),
                          jnp.inf)
            perm = perm[jnp.argsort(k, stable=True)]
        # single final ordering: invalid last
        final_key = jnp.where(out.valid[perm], 0, 1)
        perm = perm[jnp.argsort(final_key, stable=True)]
        return EventBatch(
            ts=out.ts[perm],
            cols={k: v[perm] for k, v in out.cols.items()},
            valid=out.valid[perm],
            types=out.types[perm],
        )

    def _limit_chunk(self, out: EventBatch) -> EventBatch:
        rank = jnp.cumsum(out.valid.astype(jnp.int32)) - 1
        keep = out.valid
        if self.offset is not None:
            keep = keep & (rank >= self.offset)
            rank = rank - self.offset
        if self.limit is not None:
            keep = keep & (rank < self.limit)
        return dataclasses.replace(out, valid=keep)
