"""siddhi_tpu — a TPU-native streaming & complex event processing framework.

A ground-up re-design of the capabilities of the Siddhi CEP engine (reference:
io.siddhi 5.1.x, Java) for TPU hardware: SiddhiQL streaming SQL compiled to
jitted JAX/XLA kernels over columnar event micro-batches, window/NFA state in
device ring buffers, group-by as segment reductions, keyed partitioning as a
sharded axis over a `jax.sharding.Mesh`.

Public API mirrors the reference's user surface (core/SiddhiManager.java:50):

    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime('''
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q1')
        from StockStream[price > 20.0] select symbol, price insert into OutStream;
    ''')
    rt.add_callback("OutStream", lambda events: print(events))
    rt.start()
    rt.get_input_handler("StockStream").send(("IBM", 75.6, 100))
    rt.flush()
"""

# LONG attributes and millisecond timestamps are int64 on device, matching the
# reference's Java longs; jax x64 must be enabled before any tracing happens.
import jax as _jax

_jax.config.update("jax_enable_x64", True)
# XLA:CPU's asynchronous dispatch can DEADLOCK nondeterministically when a
# jitted computation carrying a host callback (ops/search.py
# stable_argsort_bounded's pure_callback radix sort) runs concurrently with
# device_get readbacks from other threads (the async stream-callback
# decoder) — observed as a 0%-CPU wall-clock hang on single-core hosts.
# Synchronous dispatch costs nothing here: the engine is already
# one-controller-synchronous per micro-batch, and on CPU "device" compute
# shares the very cores async dispatch would overlap with. TPU and other
# backends are unaffected by this CPU-only flag.
# (SIDDHI_CPU_ASYNC_DISPATCH=1 restores jax's default for experiments.)
import os as _os

if _os.environ.get("SIDDHI_CPU_ASYNC_DISPATCH", "") != "1":
    _jax.config.update("jax_cpu_enable_async_dispatch", False)

from . import compiler  # noqa: E402
from . import io  # noqa: E402,F401  (registers source/sink/mapper extensions)
from .core import function as _function  # noqa: E402,F401  (script engines)
from .ops import stream_functions as _stream_functions  # noqa: E402,F401
from .core.dtypes import config  # noqa: E402
from .core.event import Event  # noqa: E402
from .core.stream import (  # noqa: E402
    BatchStreamCallback,
    ColumnarBlock,
    StreamCallback,
)
from .core.manager import SiddhiManager  # noqa: E402
from .errors import SiddhiError, SiddhiParserError  # noqa: E402
from .query_api import SiddhiApp  # noqa: E402
from .telemetry.logs import configure_logging as _configure_logging  # noqa: E402

_configure_logging()  # no-op unless SIDDHI_LOG_FORMAT=json

__version__ = "0.1.0"

__all__ = [
    "SiddhiManager",
    "SiddhiApp",
    "Event",
    "ColumnarBlock",
    "BatchStreamCallback",
    "StreamCallback",
    "compiler",
    "config",
    "SiddhiError",
    "SiddhiParserError",
    "__version__",
]
