"""Loader for the native host-path extension (_siddhi_native).

Builds native/columnar.c on first import (g++/cc via setuptools), caches the
shared object under siddhi_tpu/_native_build/, and degrades to the pure-Python
encoder when no toolchain is available. Set SIDDHI_TPU_NO_NATIVE=1 (or the
shorter SIDDHI_NATIVE=0) to force the Python path (useful for A/B
benchmarking the marshalling hot loop and for fallback-parity CI runs).

The cache is keyed by a hash of the C source: editing columnar.c invalidates
the cached .so and triggers a rebuild, so a stale binary can never shadow a
newer source (e.g. new validation guards silently inert)."""

from __future__ import annotations

import hashlib
import importlib
import logging
import os
import subprocess
import sys

_log = logging.getLogger("siddhi_tpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BUILD_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_native_build")
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
_SRC = os.path.join(_SRC_DIR, "columnar.c")

native = None


def _src_tag() -> str | None:
    try:
        with open(_SRC, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None


_BUILD_DIR = os.path.join(_BUILD_ROOT, _src_tag() or "nosrc")


def _try_import():
    global native
    if _BUILD_DIR not in sys.path:
        sys.path.insert(0, _BUILD_DIR)
    # the finder caches a nonexistent/empty dir entry; a fresh build would
    # otherwise be invisible until the next interpreter start
    importlib.invalidate_caches()
    import _siddhi_native
    native = _siddhi_native


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    os.makedirs(_BUILD_DIR, exist_ok=True)
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--build-lib", _BUILD_DIR],
            cwd=_SRC_DIR, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, OSError) as e:
        _log.info("native extension build failed, using Python encoder: %s", e)
        return False
    # prune superseded hash dirs (and any pre-hash-scheme loose files) so
    # iterative source edits don't accumulate orphaned binaries
    import shutil

    current = os.path.basename(_BUILD_DIR)
    try:
        for entry in os.listdir(_BUILD_ROOT):
            if entry == current:
                continue
            path = os.path.join(_BUILD_ROOT, entry)
            (shutil.rmtree if os.path.isdir(path) else os.remove)(path)
    except OSError:  # pragma: no cover — cleanup is best-effort
        pass
    return True


_DISABLED = bool(os.environ.get("SIDDHI_TPU_NO_NATIVE")) or \
    os.environ.get("SIDDHI_NATIVE", "").strip() == "0"

if not _DISABLED:
    try:
        _try_import()
    except ImportError:
        if _build():
            try:
                _try_import()
            except ImportError as e:  # pragma: no cover
                _log.info("native extension import failed after build: %s", e)


def available() -> bool:
    return native is not None
