"""Deterministic fault injection — the chaos half of the fault-tolerance layer.

Nothing in a recovery path is trustworthy until a fault has actually been
injected through it; this module makes faults *schedulable and seeded* so
tests (and bench-time soak runs) exercise retry/dead-letter/recovery code
deterministically:

    plan = FaultPlan(nth=(3, 5), exc=ConnectionUnavailableException)
    inject(rt.sinks[0], "publish", plan)     # 3rd and 5th publish raise

Failure schedules compose (any may fire on a given call):

  nth=(3, 7)            fail exactly the 3rd and 7th call (1-based)
  after=10, for_s=0.5   fail every call in the 0.5 s window that opens at
                        the first call after call #10 (fail-for-duration;
                        pass `clock=` for a virtual clock)
  p=0.02, seed=7        fail each call with probability p from a FIXED seed
                        (same seed = same schedule, run to run)
  slow_s=0.01           SLOW-CONSUMER mode: when the schedule fires, sleep
                        slow_s instead of raising — the fault is latency,
                        not an exception (overload/backpressure chaos)

`inject()` wraps a bound method on one INSTANCE (sinks, sources, persistence
stores, tables — anything), so wiring stays untouched. `apply_fault_spec()`
applies a compact spec string to a whole runtime and is wired to the
SIDDHI_FAULT_SPEC environment variable for bench soak runs:

    SIDDHI_FAULT_SPEC="sink:nth=100+200,exc=connection;store:p=0.01,seed=7"

Grammar:  spec   := clause (';' clause)*
          clause := target ':' param (',' param)*
          target := sink | source | store | table | query
          param  := nth=N[+N...] | after=N | for=SECONDS | p=PROB
                    | seed=N | exc=(connection|error) | slow=SECONDS

Targets map to: every Sink.publish, every Source.on_payload, the runtime's
PersistenceStore.save, every table's insert_batch, every query runtime's
on_batch (the `query` target is how chaos runs make a query step throw —
tripping its circuit breaker — or, with slow=, lag behind its producers so
bounded-ingress/backpressure paths engage).

Source flapping (`inject_source_flap`) exercises the pause/resume path
deterministically: every `every`-th payload pauses the source, and after
`down` more payloads it resumes (buffered payloads re-deliver).

Process-level chaos: `kill_host()` SIGKILLs a worker subprocess mid-traffic
(the multi-host failover drill's host-kill fault), and `inject_after()`
consults a plan AFTER the wrapped call returns — the lost-ack shape, where
the side effect happened but the caller never heard back.
"""

from __future__ import annotations

import functools
import os
import random
import time
from typing import Callable, Optional

from ..io.source import ConnectionUnavailableException


class InjectedFault(Exception):
    """Default non-connection injected failure."""


_EXC_BY_NAME = {
    "connection": ConnectionUnavailableException,
    "error": InjectedFault,
}


class FaultPlan:
    """A deterministic failure schedule for one wrapped call site."""

    def __init__(self, *, nth=(), after: Optional[int] = None,
                 for_s: Optional[float] = None, p: float = 0.0,
                 seed: int = 0, exc=ConnectionUnavailableException,
                 clock: Callable[[], float] = time.monotonic,
                 slow_s: Optional[float] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.nth = frozenset(int(n) for n in nth)
        self.after = int(after) if after is not None else None
        self.for_s = float(for_s) if for_s is not None else None
        self.p = float(p)
        self._rng = random.Random(seed)
        self.exc = exc
        self.clock = clock
        #: slow-consumer mode: a due call sleeps instead of raising
        self.slow_s = float(slow_s) if slow_s is not None else None
        self.sleep = sleep
        #: total calls seen / faults raised (assertable in tests)
        self.calls = 0
        self.fired = 0
        self._window_start: Optional[float] = None

    def _due(self) -> bool:
        if self.calls in self.nth:
            return True
        if self.for_s is not None and self.calls > (self.after or 0):
            if self._window_start is None:
                self._window_start = self.clock()
            if self.clock() - self._window_start < self.for_s:
                return True
        if self.p and self._rng.random() < self.p:
            return True
        return False

    def check(self, op: str = "") -> None:
        """Count one call; when the schedule says so, raise `self.exc` — or,
        in slow-consumer mode (slow_s=), stall the caller instead."""
        self.calls += 1
        if self._due():
            self.fired += 1
            if self.slow_s is not None:
                self.sleep(self.slow_s)
                return
            raise self.exc(
                f"injected fault on call #{self.calls}"
                + (f" of {op}" if op else ""))


def inject(obj, method_name: str, plan: FaultPlan) -> FaultPlan:
    """Wrap `obj.method_name` so every call first consults `plan`. Instance-
    level: only this object is affected; `restore(obj, method_name)` undoes
    it. Returns the plan for assertion convenience."""
    orig = getattr(obj, method_name)

    @functools.wraps(orig)
    def faulty(*args, **kwargs):
        plan.check(f"{type(obj).__name__}.{method_name}")
        return orig(*args, **kwargs)

    faulty.__wrapped_original__ = orig
    setattr(obj, method_name, faulty)
    return plan


def inject_after(obj, method_name: str, plan: FaultPlan) -> FaultPlan:
    """Like `inject`, but the plan is consulted AFTER the wrapped call
    completed — the side effect happened, then the caller sees the fault.
    This is the lost-ack chaos shape: a front-tier forward whose worker
    processed the frame but whose response never arrived must be retried
    AND deduplicated, not double-applied."""
    orig = getattr(obj, method_name)

    @functools.wraps(orig)
    def ack_lost(*args, **kwargs):
        result = orig(*args, **kwargs)
        plan.check(f"{type(obj).__name__}.{method_name} (post)")
        return result

    ack_lost.__wrapped_original__ = orig
    setattr(obj, method_name, ack_lost)
    return plan


def restore(obj, method_name: str) -> None:
    """Remove an injected wrapper (no-op if none present)."""
    fn = getattr(obj, method_name, None)
    orig = getattr(fn, "__wrapped_original__", None)
    if orig is not None:
        setattr(obj, method_name, orig)


def kill_host(proc) -> None:
    """SIGKILL a worker subprocess and reap it — the host-kill fault of the
    multi-host failover drill (docs/FAULT_TOLERANCE.md). SIGKILL, not
    terminate(): the dead host must get no chance to flush, close sockets,
    or say goodbye — the front tier's failure detector has to find out the
    hard way, and the WAL's torn-tail handling has to absorb whatever was
    mid-append."""
    import signal
    try:
        proc.send_signal(signal.SIGKILL)
    except (ProcessLookupError, OSError):
        pass  # already gone
    try:
        proc.wait(timeout=30)
    except Exception:  # noqa: BLE001 — unreaped zombie; the test will fail
        pass


# --------------------------------------------------------------------------- #
# spec grammar (SIDDHI_FAULT_SPEC)
# --------------------------------------------------------------------------- #

_TARGETS = ("sink", "source", "store", "table", "query")


def parse_fault_spec(spec: str) -> dict:
    """`"sink:nth=3+7;store:p=0.01,seed=7"` → {target: FaultPlan}."""
    plans: dict[str, FaultPlan] = {}
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        target, sep, body = clause.partition(":")
        target = target.strip().lower()
        if not sep or target not in _TARGETS:
            raise ValueError(
                f"bad fault spec clause {clause!r}: want "
                f"<target>:<param>,... with target in {_TARGETS}")
        kw: dict = {}
        for param in filter(None, (p.strip() for p in body.split(","))):
            key, sep2, val = param.partition("=")
            if not sep2:
                raise ValueError(f"bad fault spec param {param!r}")
            key = key.strip().lower()
            val = val.strip()
            if key == "nth":
                kw["nth"] = tuple(int(v) for v in val.split("+"))
            elif key == "after":
                kw["after"] = int(val)
            elif key == "for":
                kw["for_s"] = float(val)
            elif key == "p":
                kw["p"] = float(val)
            elif key == "seed":
                kw["seed"] = int(val)
            elif key == "slow":
                kw["slow_s"] = float(val)
            elif key == "exc":
                try:
                    kw["exc"] = _EXC_BY_NAME[val.lower()]
                except KeyError:
                    raise ValueError(
                        f"bad fault spec exc {val!r}: want one of "
                        f"{tuple(_EXC_BY_NAME)}") from None
            else:
                raise ValueError(f"unknown fault spec param {key!r}")
        plans[target] = FaultPlan(**kw)
    return plans


def apply_fault_spec(runtime, spec: Optional[str] = None) -> dict:
    """Inject a parsed spec into a built runtime: sinks' publish, sources'
    on_payload, the persistence store's save, tables' insert_batch. `spec`
    defaults to $SIDDHI_FAULT_SPEC; returns the {target: FaultPlan} map
    ({} when no spec is set) so callers can assert on .calls/.fired.

    Apply BEFORE runtime.start() when targeting sources: transports capture
    the on_payload callback at connect time, so a wrapper injected after
    start() never sees the traffic."""
    if spec is None:
        spec = os.environ.get("SIDDHI_FAULT_SPEC", "")
    if not spec:
        return {}
    plans = parse_fault_spec(spec)
    for target, plan in plans.items():
        if target == "sink":
            for sink in runtime.sinks:
                inject(sink, "publish", plan)
        elif target == "source":
            for source in runtime.sources:
                inject(source, "on_payload", plan)
        elif target == "store":
            store = runtime.persistence_store
            if store is not None:
                inject(store, "save", plan)
        elif target == "table":
            for table in runtime.tables.values():
                if hasattr(table, "insert_batch"):
                    inject(table, "insert_batch", plan)
        elif target == "query":
            for qr in runtime.query_runtimes.values():
                inject(qr, "on_batch", plan)
    return plans


# --------------------------------------------------------------------------- #
# source flapping (pause/resume chaos)
# --------------------------------------------------------------------------- #


class SourceFlapPlan:
    """Deterministic pause/resume schedule for one source: every `every`-th
    payload PAUSES the source (subsequent payloads buffer in its bounded
    pending queue), and after `down` more payloads it RESUMES — buffered
    payloads re-deliver in order. `flaps` counts completed pause→resume
    cycles for assertions."""

    def __init__(self, *, every: int, down: int = 1) -> None:
        if every < 1 or down < 1:
            raise ValueError("every and down must be >= 1")
        self.every = int(every)
        self.down = int(down)
        self.calls = 0
        self.flaps = 0
        self._down_left = 0

    def on_call(self, source) -> None:
        self.calls += 1
        if source.paused:
            self._down_left -= 1
            if self._down_left <= 0:
                source.resume()  # buffered payloads re-deliver first
                self.flaps += 1
        elif self.calls % self.every == 0:
            source.pause()
            self._down_left = self.down


def inject_source_flap(source, plan: SourceFlapPlan) -> SourceFlapPlan:
    """Wrap `source.on_payload` so the flap schedule runs before each
    delivery. Inject BEFORE runtime.start() (transports capture on_payload
    at connect time); `restore(source, "on_payload")` undoes it."""
    orig = source.on_payload

    @functools.wraps(orig)
    def flapping(payload):
        plan.on_call(source)
        return orig(payload)

    flapping.__wrapped_original__ = orig
    source.on_payload = flapping
    return plan
