"""Named locks + optional lockdep-style runtime lock-order verification.

Every lock in the engine is created through this module with a stable
``"<subsystem>.<role>"`` name (the catalog lives in docs/CONCURRENCY.md).
With ``SIDDHI_LOCK_CHECKS`` unset (the default) the factories return the
raw ``threading`` primitives — zero wrapper, zero overhead, so the
telemetry A/B budget is untouched. With ``SIDDHI_LOCK_CHECKS=1`` each
lock is wrapped with a tracker that maintains:

* a per-thread held-stack of lock *names*;
* a global acquisition-order digraph keyed by name (instances sharing a
  name unify — the two controller locks live during a blue-green swap
  are one node, and re-entrant RLock acquisitions add no edge);
* cycle detection over that digraph, reporting *potential* deadlocks on
  the first inconsistent ordering without needing the deadlock to fire;
* held-across-blocking hazards: instrumented blocking sites (device
  dispatch, WAL fsync, bounded-queue put, HTTP handling) call
  :func:`note_blocking` and any held lock not on the site's allow-list
  is reported once.

Findings surface in ``statistics_report()['lockdep']`` and are logged on
first detection. ``SIDDHI_SCHED_FUZZ=<seed>`` additionally arms seeded
preemption points at every tracked acquisition (schedule fuzzing in the
style of util/faults.py): the perturbation schedule — which acquisitions
stall, and for how long — is a pure function of (seed, lock name,
per-thread acquisition counter), so a failing seed replays the same
pressure pattern.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
import zlib
from typing import Iterable, Optional

log = logging.getLogger("siddhi_tpu.locks")

__all__ = [
    "named_lock", "named_rlock", "named_condition",
    "checks_enabled", "enable_checks",
    "note_blocking", "lockdep_report", "lockdep_reset",
    "set_schedule_fuzz", "schedule_fuzz_seed",
]


def _env_truthy(v: Optional[str]) -> bool:
    return bool(v) and v.strip().lower() not in ("0", "false", "no", "off")


#: module switch — read at factory-call time so tests can flip it before
#: constructing the locks they want tracked.
_CHECKS = _env_truthy(os.environ.get("SIDDHI_LOCK_CHECKS"))

#: schedule-fuzz seed (None = off), from SIDDHI_SCHED_FUZZ.
_FUZZ_SEED: Optional[int] = None
_fz = os.environ.get("SIDDHI_SCHED_FUZZ", "").strip()
if _fz:
    try:
        _FUZZ_SEED = int(_fz)
    except ValueError:  # pragma: no cover — operator typo
        log.warning("SIDDHI_SCHED_FUZZ=%r is not an integer; ignored", _fz)


def checks_enabled() -> bool:
    return _CHECKS


def enable_checks(on: bool = True) -> None:
    """Flip lockdep tracking for locks created *after* this call (tests)."""
    global _CHECKS
    _CHECKS = bool(on)


def set_schedule_fuzz(seed: Optional[int]) -> None:
    global _FUZZ_SEED
    _FUZZ_SEED = None if seed is None else int(seed)


def schedule_fuzz_seed() -> Optional[int]:
    return _FUZZ_SEED


# --------------------------------------------------------------------------
# lockdep state (only touched when checks are enabled)
# --------------------------------------------------------------------------

_tls = threading.local()           # .stack: list[str] of held lock names
_reg = threading.Lock()            # guards every structure below
_lock_names: dict[str, int] = {}   # name -> instances created
_edges: dict[str, set] = {}        # name -> names acquired while held
_edge_site: dict = {}              # (a, b) -> formatted stack (first seen)
_cycles: list = []                 # recorded potential-deadlock findings
_cycle_keys: set = set()
_hazards: list = []                # held-across-blocking findings
_hazard_keys: set = set()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _fuzz_counter() -> int:
    n = getattr(_tls, "fuzz_n", 0)
    _tls.fuzz_n = n + 1
    return n


def _preempt(name: str) -> None:
    """Seeded preemption point, executed before a tracked acquisition.

    The decision is a CRC over (seed, name, per-thread acquisition index)
    — deterministic per thread, independent of wall clock. Roughly one in
    four acquisitions stalls 0.1–0.8 ms, widening the race windows the OS
    scheduler would otherwise almost never expose.
    """
    seed = _FUZZ_SEED
    if seed is None:
        return
    h = zlib.crc32(("%d:%s:%d" % (seed, name, _fuzz_counter())).encode())
    if h % 4 == 0:
        time.sleep(0.0001 * (1 + (h >> 8) % 8))


def _find_path(src: str, dst: str) -> Optional[list]:
    """DFS path src ⇝ dst over _edges (caller holds _reg)."""
    seen = {src}
    path = [src]

    def walk(node: str) -> bool:
        for nxt in sorted(_edges.get(node, ())):
            if nxt == dst:
                path.append(nxt)
                return True
            if nxt not in seen:
                seen.add(nxt)
                path.append(nxt)
                if walk(nxt):
                    return True
                path.pop()
        return False

    return path if walk(src) else None


def _site(skip: int = 3) -> str:
    return "".join(traceback.format_stack(limit=16)[:-skip])


def _on_acquired(name: str) -> None:
    """Bookkeeping after a tracked lock was acquired by this thread."""
    stack = _stack()
    if name in stack:           # re-entrant (RLock) or same-name instance
        stack.append(name)      # (cross-app controller nesting): no edge
        return
    if stack:
        a, b = stack[-1], name
        with _reg:
            out = _edges.setdefault(a, set())
            if b not in out:
                out.add(b)
                _edge_site[(a, b)] = _site()
                back = _find_path(b, a)
                if back is not None:
                    cyc = back  # b ... a, closing edge a->b
                    key = frozenset(cyc)
                    if key not in _cycle_keys:
                        _cycle_keys.add(key)
                        finding = {
                            "kind": "lock-order-inversion",
                            "cycle": cyc + [cyc[0]],
                            "edge": [a, b],
                            "this_site": _edge_site[(a, b)],
                            "reverse_site": _edge_site.get(
                                (cyc[0], cyc[1]), ""),
                        }
                        _cycles.append(finding)
                        log.warning(
                            "lockdep: potential deadlock — inconsistent "
                            "lock order %s (new edge %s -> %s)\n%s",
                            " -> ".join(finding["cycle"]), a, b,
                            finding["this_site"])
    stack.append(name)


def _on_released(name: str) -> None:
    stack = _stack()
    # release order can differ from acquire order; drop the innermost entry
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


def note_blocking(kind: str, allow: Iterable[str] = ()) -> None:
    """Declare that the calling thread is about to block (``kind`` names
    the operation: "device_dispatch", "wal.fsync", "queue.put",
    "http.handle", ...). Under lock checks, every held lock not in
    ``allow`` is reported as a held-across-blocking hazard (once per
    (kind, lock set)). No-op — one bool test — when checks are off."""
    if not _CHECKS:
        return
    stack = _stack()
    if not stack:
        return
    held = []
    for n in stack:
        if n not in allow and n not in held:
            held.append(n)
    if not held:
        return
    key = (kind, tuple(held))
    with _reg:
        if key in _hazard_keys:
            return
        _hazard_keys.add(key)
        finding = {
            "kind": "held-across-blocking",
            "blocking": kind,
            "held": held,
            "site": _site(),
        }
        _hazards.append(finding)
    log.warning("lockdep: lock(s) %s held across blocking %r\n%s",
                held, kind, finding["site"])


def lockdep_report() -> dict:
    """Snapshot of the lockdep state; shape carried by
    ``statistics_report()['lockdep']``."""
    with _reg:
        return {
            "enabled": _CHECKS,
            "locks": dict(_lock_names),
            "edges": sorted((a, b) for a, outs in _edges.items()
                            for b in outs),
            "cycles": list(_cycles),
            "hazards": list(_hazards),
            "fuzz_seed": _FUZZ_SEED,
        }


def lockdep_reset() -> None:
    """Clear the digraph and findings (tests). Held-stacks of live
    threads are left alone; call between quiesced phases."""
    with _reg:
        _edges.clear()
        _edge_site.clear()
        _cycles.clear()
        _cycle_keys.clear()
        _hazards.clear()
        _hazard_keys.clear()


# --------------------------------------------------------------------------
# tracked primitives
# --------------------------------------------------------------------------

class _TrackedLock:
    """threading.Lock wrapper feeding the lockdep tracker."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner=None) -> None:
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _preempt(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _on_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _on_released(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"<named lock {self.name!r} {self._inner!r}>"


class _TrackedRLock:
    """threading.RLock wrapper; exposes _is_owned() for the junction's
    controller-ownership fast path (stream.py _lock_owned)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str) -> None:
        self.name = name
        self._inner = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _preempt(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _on_acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _on_released(self.name)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<named rlock {self.name!r} {self._inner!r}>"


class _TrackedCondition:
    """Condition over a tracked lock. wait()/wait_for() fully release the
    underlying lock, so the held-stack entries for the name are popped for
    the duration and restored after re-acquisition."""

    __slots__ = ("name", "_lock", "_cv")

    def __init__(self, name: str, lock=None) -> None:
        self.name = name
        if lock is None:
            lock = _TrackedRLock(name)
        self._lock = lock
        # build the real Condition on the *raw* primitive; bookkeeping is
        # done here so Condition's internal _release_save path stays fast
        self._cv = threading.Condition(lock._inner)

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()

    def _pop_all(self) -> int:
        stack = _stack()
        n = stack.count(self.name)
        if n:
            _tls.stack = [s for s in stack if s != self.name]
        return n

    def _push(self, n: int) -> None:
        if n:
            _stack().extend([self.name] * n)

    def wait(self, timeout: Optional[float] = None) -> bool:
        n = self._pop_all()
        try:
            return self._cv.wait(timeout)
        finally:
            self._push(n)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        n = self._pop_all()
        try:
            return self._cv.wait_for(predicate, timeout)
        finally:
            self._push(n)

    def notify(self, n: int = 1) -> None:
        self._cv.notify(n)

    def notify_all(self) -> None:
        self._cv.notify_all()

    def __repr__(self) -> str:  # pragma: no cover
        return f"<named condition {self.name!r}>"


# --------------------------------------------------------------------------
# factories
# --------------------------------------------------------------------------

def _register(name: str) -> None:
    with _reg:
        _lock_names[name] = _lock_names.get(name, 0) + 1


def named_lock(name: str):
    """A mutex named ``"<subsystem>.<role>"``. Raw ``threading.Lock`` by
    default; lockdep-tracked under SIDDHI_LOCK_CHECKS=1."""
    if not _CHECKS:
        return threading.Lock()
    _register(name)
    return _TrackedLock(name)


def named_rlock(name: str):
    """Re-entrant variant of :func:`named_lock`."""
    if not _CHECKS:
        return threading.RLock()
    _register(name)
    return _TrackedRLock(name)


def named_condition(name: str, lock=None):
    """Condition variable over a named lock. ``lock`` may be a tracked
    lock created by this module (shared conditions) or None for a private
    re-entrant lock."""
    if not _CHECKS:
        return threading.Condition(lock)
    _register(name)
    if lock is not None and not isinstance(lock, (_TrackedLock,
                                                  _TrackedRLock)):
        # raw primitive slipped in (checks flipped mid-run): wrap it
        lock = _TrackedLock(name, inner=lock)
    return _TrackedCondition(name, lock)
