"""Force JAX onto a virtual multi-device CPU platform.

Env-var overrides alone are not enough in this image — the axon TPU plugin
registers itself regardless of ``JAX_PLATFORMS`` — so the platform is also
forced through ``jax.config``, and an already-initialised backend on the
wrong platform (or with too few devices) is cleared so it re-initialises
under the new settings.

Used by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` (the
driver calls the latter directly, possibly after jax has already been
touched on the real TPU).
"""

from __future__ import annotations

import os
import re


def set_host_device_count_flag(flags: str, n_devices: int) -> str:
    """Return ``flags`` with ``--xla_force_host_platform_device_count`` set
    to exactly ``n_devices``, replacing any inherited count rather than
    trusting it (it may be smaller than what we need; older jax has no
    jax_num_cpu_devices config, so XLA_FLAGS must carry the right value)."""
    flag = "--xla_force_host_platform_device_count"
    if flag in flags:
        return re.sub(rf"{flag}=\S+", f"{flag}={n_devices}", flags)
    return (flags + f" {flag}={n_devices}").strip()


def force_cpu_platform(n_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = set_host_device_count_flag(
        os.environ.get("XLA_FLAGS", ""), n_devices)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.extend.backend import clear_backends

    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        pass  # older jax: XLA_FLAGS alone must suffice
    except RuntimeError:
        # Backends were already initialised; reset them and set the count
        # before they re-initialise.
        clear_backends()
        jax.config.update("jax_num_cpu_devices", n_devices)

    try:
        devices = jax.devices()
        ok = len(devices) >= n_devices and all(
            d.platform == "cpu" for d in devices)
    except Exception:
        ok = False
    if not ok:
        clear_backends()
