"""Deployment configuration — ConfigManager / ConfigReader SPI.

Reference: core/util/config/ — ConfigManager + ConfigReader SPI,
InMemoryConfigManager, YAMLConfigManager.java:40 (parses the deployment YAML's
`extensions:` list into per-(namespace,name) property maps, plus `refs:` and
root-level system configs). Extensions receive a ConfigReader at init; here
the IO wiring layers config properties UNDER annotation options (annotation
wins), matching the reference's configReader precedence.
"""

from __future__ import annotations

from typing import Optional


class ConfigReader:
    """Per-extension property view (reference: ConfigReader SPI)."""

    def __init__(self, properties: Optional[dict] = None) -> None:
        self._props = dict(properties or {})

    def read_config(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._props.get(key, default)

    def get_all_configs(self) -> dict:
        return dict(self._props)


class ConfigManager:
    """SPI (reference: ConfigManager)."""

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        raise NotImplementedError

    def extract_system_configs(self, name: str) -> dict:
        raise NotImplementedError

    def extract_property(self, name: str) -> Optional[str]:
        raise NotImplementedError


class InMemoryConfigManager(ConfigManager):
    """Reference: InMemoryConfigManager — configs keyed 'namespace.name.key'."""

    def __init__(self, configs: Optional[dict] = None,
                 system_configs: Optional[dict] = None) -> None:
        self._configs = dict(configs or {})
        self._system = dict(system_configs or {})

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        prefix = f"{namespace}.{name}."
        return ConfigReader({
            k[len(prefix):]: v for k, v in self._configs.items()
            if k.startswith(prefix)})

    def extract_system_configs(self, name: str) -> dict:
        return dict(self._system.get(name, {}))

    def extract_property(self, name: str) -> Optional[str]:
        return self._configs.get(name)


class YAMLConfigManager(ConfigManager):
    """Reference: YAMLConfigManager.java:40. YAML layout::

        extensions:
          - extension:
              name: inMemory
              namespace: source
              properties:
                topic: defaultTopic
        refs:
          - ref:
              name: store1
              type: rdbms
              properties: {...}
        properties:
          some.system.property: value
    """

    def __init__(self, yaml_text: Optional[str] = None,
                 yaml_path: Optional[str] = None) -> None:
        import yaml
        if yaml_text is None:
            if yaml_path is None:
                raise ValueError("need yaml_text or yaml_path")
            with open(yaml_path) as f:
                yaml_text = f.read()
        data = yaml.safe_load(yaml_text) or {}
        self._extensions: dict[tuple[str, str], dict] = {}
        for item in data.get("extensions", []) or []:
            ext = item.get("extension", item)
            key = (str(ext.get("namespace", "")).lower(),
                   str(ext.get("name", "")).lower())
            self._extensions[key] = dict(ext.get("properties", {}) or {})
        self._refs: dict[str, dict] = {}
        for item in data.get("refs", []) or []:
            ref = item.get("ref", item)
            self._refs[str(ref.get("name"))] = {
                "type": ref.get("type"),
                "properties": dict(ref.get("properties", {}) or {})}
        self._properties = dict(data.get("properties", {}) or {})

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader(
            self._extensions.get((namespace.lower(), name.lower()), {}))

    def extract_system_configs(self, name: str) -> dict:
        ref = self._refs.get(name)
        if ref is None:
            return {}
        out = dict(ref["properties"])
        out["type"] = ref["type"]
        return out

    def extract_property(self, name: str) -> Optional[str]:
        return self._properties.get(name)
