"""Extension documentation generator.

Reference: modules/siddhi-doc-gen/ — a Maven mojo that renders markdown for
every @Extension's metadata. Here: walk the extension registry and render one
markdown document grouped by kind, with each extension's docstring.

Usage:  python -m siddhi_tpu.util.docgen [output.md]
"""

from __future__ import annotations

import inspect

from ..extension.registry import GLOBAL, ExtensionKind, Registry

_KIND_TITLES = {
    ExtensionKind.WINDOW: "Windows",
    ExtensionKind.AGGREGATOR: "Aggregators",
    ExtensionKind.FUNCTION: "Functions",
    ExtensionKind.STREAM_FUNCTION: "Stream functions",
    ExtensionKind.STREAM_PROCESSOR: "Stream processors",
    ExtensionKind.SOURCE: "Sources",
    ExtensionKind.SINK: "Sinks",
    ExtensionKind.SOURCE_MAPPER: "Source mappers",
    ExtensionKind.SINK_MAPPER: "Sink mappers",
    ExtensionKind.DISTRIBUTION_STRATEGY: "Sink distribution strategies",
    ExtensionKind.SCRIPT: "Script engines",
    ExtensionKind.TABLE: "Tables",
    ExtensionKind.STORE: "Stores",
    ExtensionKind.INCREMENTAL_AGGREGATOR: "Incremental aggregators",
}


def _describe(impl) -> str:
    doc = inspect.getdoc(impl)
    auto = f"{type(impl).__name__}(" if not inspect.isclass(impl) else None
    if not doc or (auto and doc.startswith(auto)):
        # dataclass-generated repr docstring: describe the factory instead
        make = getattr(impl, "make", None)
        doc = inspect.getdoc(make) if make is not None else None
    if not doc:
        return "_(no documentation)_"
    return doc.split("\n\n")[0].replace("\n", " ")


def generate_markdown(registry: Registry = GLOBAL) -> str:
    lines = ["# siddhi_tpu extension reference", "",
             "Generated from the extension registry "
             "(the analogue of the reference's siddhi-doc-gen mojo over "
             "@Extension metadata).", ""]
    by_kind: dict[ExtensionKind, list] = {}
    for (kind, key), impl in sorted(registry._entries.items(),
                                    key=lambda kv: (kv[0][0].value, kv[0][1])):
        by_kind.setdefault(kind, []).append((key, impl))
    for kind, entries in by_kind.items():
        lines.append(f"## {_KIND_TITLES.get(kind, kind.value)}")
        lines.append("")
        for key, impl in entries:
            lines.append(f"### `{key}`")
            lines.append("")
            meta = registry._meta.get((kind, key))
            if meta is not None and meta.description:
                lines.append(meta.description)
            else:
                lines.append(_describe(impl))
            lines.append("")
            if meta is not None and meta.parameters:
                # @Parameter tables, like the reference doc-gen renders
                lines.append("| Parameter | Type | Optional | Default |"
                             " Description |")
                lines.append("|---|---|---|---|---|")
                for p in meta.parameters:
                    dflt = "" if p.default is None else repr(p.default)
                    lines.append(
                        f"| `{p.name}` | {' / '.join(p.types)} | "
                        f"{'yes' if p.optional else 'no'} | {dflt} | "
                        f"{p.doc} |")
                if meta.repeat_last:
                    lines.append("")
                    lines.append("_The last parameter may repeat._")
                lines.append("")
    return "\n".join(lines)


def main(argv=None) -> None:
    import sys
    argv = argv if argv is not None else sys.argv[1:]
    out = argv[0] if argv else "docs/extensions.md"
    import os
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    import siddhi_tpu  # noqa: F401 — trigger all built-in registrations
    with open(out, "w") as f:
        f.write(generate_markdown())
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
