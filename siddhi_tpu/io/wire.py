"""SXF1 — the zero-copy binary wire format for columnar stream frames.

The JSON ingestion path (`POST .../streams/<s>` with {"events": [[...]]})
decodes every row into Python objects before the engine re-encodes them into
columns — exactly the per-row host work the ingress pipeline exists to
avoid. SXF1 carries the columns themselves: a length-prefixed frame whose
numeric payloads are raw little-endian arrays that `np.frombuffer` views
without copying, and whose string columns are dictionary-encoded (distinct
values + int32 indexes), so the server interns per DISTINCT value instead of
per row and the indexes map onto ring slots untouched.

Framing (all integers little-endian):

    body    := frame*
    frame   := u32 payload_len | payload
    payload := 'SXF1' | u8 flags | u16 n_cols | u32 n_rows
               | [ i64 ts[n_rows]          when flags bit0 (has_ts) ]
               | col*
    col     := u8 typecode | coldata
    coldata := raw values, width(typecode) * n_rows      (b i l f d)
             | u32 dict_n
               | dict_n * (u16 byte_len | utf8 bytes)    (s: dictionary)
               | i32 idx[n_rows]                         (-1 = null)

Type codes match native/columnar.c: b=1 byte (bool/int8), i=int32,
l=int64, f=float32, d=float64, s=string (dictionary + int32 indexes).
Columns appear in stream-attribute declaration order; OBJECT attributes are
not representable. Numeric nulls are the engine's null sentinels
(core/dtypes.null_value), encoded by the producer.

The decoder returns numpy VIEWS over the request buffer for numeric columns
and ('dict', values, idx_view) triples for strings — the form
IngressPipeline.submit_columns consumes directly. Without a pipeline the
same frame materializes through the ordinary send_columns path, so the two
ingestion modes stay byte-identical downstream.
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional, Sequence

import numpy as np

MAGIC = b"SXF1"
FLAG_HAS_TS = 0x01

#: typecode -> (byte width, little-endian numpy dtype for the raw payload)
_WIRE_DTYPES = {
    "b": (1, np.dtype("u1")),
    "i": (4, np.dtype("<i4")),
    "l": (8, np.dtype("<i8")),
    "f": (4, np.dtype("<f4")),
    "d": (8, np.dtype("<f8")),
}

_NP_TYPECODE = {"bool": "b", "int8": "b", "int32": "i", "int64": "l",
                "float32": "f", "float64": "d"}


class WireFormatError(ValueError):
    pass


def schema_plan(definition) -> list[tuple[str, np.dtype, str]]:
    """Per-attribute (name, host dtype, wire typecode) in declaration
    order. Raises for schemas SXF1 cannot carry (OBJECT attrs)."""
    from ..core import dtypes as _dt
    from ..query_api.definition import AttributeType
    import jax.numpy as jnp

    plan = []
    for a in definition.attributes:
        if a.type == AttributeType.OBJECT:
            raise WireFormatError(
                f"stream {definition.id!r}: OBJECT attribute {a.name!r} "
                "has no columnar wire representation")
        if a.type == AttributeType.STRING:
            plan.append((a.name, np.dtype(np.int32), "s"))
            continue
        dt = np.dtype(jnp.dtype(_dt.device_dtype(a.type)).name)
        code = _NP_TYPECODE.get(dt.name)
        if code is None:  # pragma: no cover — no such scalar type today
            raise WireFormatError(f"unsupported dtype {dt} for {a.name!r}")
        plan.append((a.name, dt, code))
    return plan


# ------------------------------------------------------------------ encoding


def encode_frame(plan: Sequence[tuple[str, np.dtype, str]],
                 columns: dict, n: int,
                 ts: Optional[np.ndarray] = None) -> bytes:
    """Encode one frame. String columns accept str/None sequences (object
    arrays) — dictionary-encoded here, producer-side, so the server never
    sees per-row strings."""
    parts = [MAGIC,
             struct.pack("<BHI", FLAG_HAS_TS if ts is not None else 0,
                         len(plan), n)]
    if ts is not None:
        ts = np.ascontiguousarray(np.asarray(ts)[:n], dtype="<i8")
        parts.append(ts.tobytes())
    for name, dt, code in plan:
        if name not in columns:
            raise WireFormatError(f"encode_frame: missing column {name!r}")
        src = columns[name]
        if code == "s":
            arr = np.asarray(src, dtype=object)[:n]
            # first-appearance dictionary: deterministic, so re-encoding
            # the same rows yields the same bytes
            dict_pos: dict[str, int] = {}
            idx = np.empty(n, dtype="<i4")
            for i, v in enumerate(arr):
                if v is None:
                    idx[i] = -1
                    continue
                p = dict_pos.get(v)
                if p is None:
                    p = len(dict_pos)
                    dict_pos[v] = p
                idx[i] = p
            parts.append(struct.pack("<BI", ord(code), len(dict_pos)))
            for v in dict_pos:
                raw = v.encode("utf-8")
                if len(raw) > 0xFFFF:
                    raise WireFormatError(
                        f"string value too long for SXF1 ({len(raw)} bytes)")
                parts.append(struct.pack("<H", len(raw)))
                parts.append(raw)
            parts.append(idx.tobytes())
        else:
            width, wdt = _WIRE_DTYPES[code]
            raw = np.ascontiguousarray(np.asarray(src)[:n], dtype=dt)
            if raw.dtype.itemsize != width:  # pragma: no cover — plan bug
                raise WireFormatError(f"width mismatch for {name!r}")
            parts.append(struct.pack("<B", ord(code)))
            parts.append(raw.astype(wdt, copy=False).tobytes())
    payload = b"".join(parts)
    return struct.pack("<I", len(payload)) + payload


def encode_frames(plan, columns: dict, n: int,
                  ts: Optional[np.ndarray] = None,
                  chunk: Optional[int] = None) -> bytes:
    """Encode `n` rows as one frame, or as ceil(n/chunk) frames when
    `chunk` is given (multi-frame bodies exercise streaming decode)."""
    if chunk is None or chunk >= n:
        return encode_frame(plan, columns, n, ts)
    out = []
    for s in range(0, n, chunk):
        m = min(chunk, n - s)
        cols_c = {k: np.asarray(v)[s:s + m] for k, v in columns.items()}
        ts_c = None if ts is None else np.asarray(ts)[s:s + m]
        out.append(encode_frame(plan, cols_c, m, ts_c))
    return b"".join(out)


# ------------------------------------------------------------------ decoding


def iter_frames(body) -> Iterator[memoryview]:
    """Yield each frame's payload as a memoryview (no copies)."""
    mv = memoryview(body)
    off = 0
    total = len(mv)
    while off < total:
        if total - off < 4:
            raise WireFormatError("truncated frame length prefix")
        (plen,) = struct.unpack_from("<I", mv, off)
        off += 4
        if total - off < plen:
            raise WireFormatError(
                f"truncated frame: need {plen} bytes, have {total - off}")
        yield mv[off:off + plen]
        off += plen


def decode_frame(payload: memoryview, plan) -> tuple[
        Optional[np.ndarray], dict, int]:
    """Decode one payload against `plan`. Returns (ts or None, columns, n)
    where numeric columns are zero-copy views over the payload and string
    columns are ('dict', values: list[str|None], idx: int32 view) triples —
    exactly what IngressPipeline.submit_columns takes."""
    mv = memoryview(payload)
    if len(mv) < 11 or bytes(mv[:4]) != MAGIC:
        raise WireFormatError("bad frame magic (want 'SXF1')")
    flags, n_cols, n = struct.unpack_from("<BHI", mv, 4)
    off = 11
    if n_cols != len(plan):
        raise WireFormatError(
            f"frame has {n_cols} columns, stream declares {len(plan)}")
    ts = None
    if flags & FLAG_HAS_TS:
        end = off + 8 * n
        if len(mv) < end:
            raise WireFormatError("truncated timestamp block")
        ts = np.frombuffer(mv[off:end], dtype="<i8")
        off = end
    cols: dict = {}
    for name, dt, code in plan:
        if len(mv) < off + 1:
            raise WireFormatError(f"truncated column header for {name!r}")
        got = chr(mv[off])
        off += 1
        if got != code:
            raise WireFormatError(
                f"column {name!r}: frame typecode {got!r} != schema {code!r}")
        if code == "s":
            (dict_n,) = struct.unpack_from("<I", mv, off)
            off += 4
            values: list = []
            for _ in range(dict_n):
                (blen,) = struct.unpack_from("<H", mv, off)
                off += 2
                values.append(str(mv[off:off + blen], "utf-8"))
                off += blen
            end = off + 4 * n
            if len(mv) < end:
                raise WireFormatError(f"truncated index block for {name!r}")
            idx = np.frombuffer(mv[off:end], dtype="<i4")
            off = end
            cols[name] = ("dict", values, idx)
        else:
            width, wdt = _WIRE_DTYPES[code]
            end = off + width * n
            if len(mv) < end:
                raise WireFormatError(f"truncated data block for {name!r}")
            raw = np.frombuffer(mv[off:end], dtype=wdt)
            cols[name] = raw if raw.dtype == dt else raw.view(dt) \
                if raw.dtype.itemsize == dt.itemsize else raw.astype(dt)
            off = end
    return ts, cols, n


def materialize_strings(col) -> np.ndarray:
    """('dict', values, idx) -> object array of str/None (the fallback
    path's send_columns input)."""
    _, values, idx = col
    lut = np.empty(len(values) + 1, dtype=object)
    lut[0] = None
    lut[1:] = values
    return lut[idx.astype(np.int64) + 1]


def subset_dict_column(values, idx, sel) -> tuple:
    """A ('dict', values, idx) column restricted to boolean mask `sel`,
    with the value list COMPACTED to just the entries the surviving rows
    reference — the shard router's pre-interning subset: a shard's string
    table interns only the keys routed to it, never the whole frame
    dictionary."""
    idx = np.asarray(idx)
    sub = idx[sel]
    valid = sub >= 0
    used = np.unique(sub[valid]) if valid.any() else \
        np.zeros(0, dtype=np.int64)
    remap = np.full(len(values), -1, dtype=np.int32)
    remap[used] = np.arange(len(used), dtype=np.int32)
    new_idx = np.where(valid, remap[np.clip(sub, 0, None)],
                       np.int32(-1)).astype(np.int32)
    return ("dict", [values[int(i)] for i in used], new_idx)


def deliver_frames(handler, body) -> int:
    """Decode every frame in `body` and feed it through `handler`'s
    junction: straight into the ingress pipeline when one is running
    (zero-copy: numeric views + dictionary interning per distinct value),
    else through the ordinary send_columns path. Returns rows accepted.

    A handler carrying its own `deliver_frames` (the shard plane's routing
    handler) owns the whole decode-route-deliver sequence: frames are
    hashed on ORIGINAL dictionary values and split per shard BEFORE any
    interning."""
    if hasattr(handler, "deliver_frames"):
        return handler.deliver_frames(body)
    j = handler.junction
    plan = schema_plan(j.definition)
    total = 0
    for payload in iter_frames(body):
        ts, cols, n = decode_frame(payload, plan)
        if n == 0:
            continue
        if ts is None:
            now = j.ctx.timestamp_generator.current_time()
            ts = np.full(n, now, dtype=np.int64)
        p = j._pipeline
        if p is not None and j.wal is None and not j.taps \
                and not j._lock_owned():
            j.ctx.timestamp_generator.observe_event_time(int(ts[:n].max()))
            done = p.submit_columns(ts, cols, n, frame=True)
            if done >= n:
                total += n
                continue
            # pipeline stopping: remainder through the synchronous path
            ts = ts[done:]
            cols = {k: (v if isinstance(v, tuple) else v[done:])
                    for k, v in cols.items()}
            cols = {k: (("dict", v[1], v[2][done:])
                        if isinstance(v, tuple) else v)
                    for k, v in cols.items()}
            n -= done
            total += done
        plain = {k: (materialize_strings(v) if isinstance(v, tuple) else v)
                 for k, v in cols.items()}
        handler.send_columns(plain, timestamps=ts, count=n)
        total += n
    return total
