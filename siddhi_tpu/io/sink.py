"""Sinks, sink mappers & distributed transports — stream events out.

Reference: core/stream/output/sink/Sink.java:62 (publish + retry),
SinkMapper.java:44 (event → payload with {{attr}} templating —
core/util/transport/TemplateBuilder.java), InMemorySink.java:64, LogSink.java,
distributed/DistributedTransport.java + RoundRobin/Partitioned/Broadcast
DistributionStrategy (core/util/transport/), SinkHandler SPI.
"""

from __future__ import annotations

import json as _json
import logging
import re
import time
import zlib
from typing import Optional

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from .broker import InMemoryBroker
from .source import BackoffRetryCounter, ConnectionUnavailableException

log = logging.getLogger("siddhi_tpu")


class SinkMapper:
    """Row → payload SPI (reference: SinkMapper.java:44)."""

    def init(self, stream_definition, options: dict,
             payload_template: Optional[str]) -> None:
        self.definition = stream_definition
        self.options = options
        self.payload_template = payload_template

    def map(self, row: tuple) -> object:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, row: tuple) -> object:
        return row


class JsonSinkMapper(SinkMapper):
    """@map(type='json') — {"event": {attr: value}}."""

    def map(self, row: tuple) -> object:
        ev = {a.name: v for a, v in zip(self.definition.attributes, row)}
        return _json.dumps({"event": ev})


_TEMPLATE_RE = re.compile(r"\{\{(\w+)\}\}")


class TextSinkMapper(SinkMapper):
    """@map(type='text', @payload('price is {{price}}')) — the reference's
    TemplateBuilder {{attr}} substitution (core/util/transport/TemplateBuilder.java)."""

    def map(self, row: tuple) -> object:
        values = {a.name: v for a, v in zip(self.definition.attributes, row)}
        if self.payload_template is None:
            return ", ".join(f"{k}:{v}" for k, v in values.items())
        return _TEMPLATE_RE.sub(lambda m: str(values[m.group(1)]),
                                self.payload_template)


class Sink:
    """Transport SPI (reference: Sink.java:62 — publish with
    ConnectionUnavailableException retry via BackoffRetryCounter).

    Egress fault policy (`@sink(..., on.error='WAIT')`, reference
    OnErrorAction + the junction's @OnError matrix):

      LOG     log the failed event, count it as dropped, continue (default)
      WAIT    on ConnectionUnavailableException: buffer the in-flight rest
              of the batch, reconnect with exponential backoff, re-publish;
              after `max.retries` reconnects dead-letter the remainder to
              the ErrorStore (never a silent drop)
      STREAM  route the failed event + error message into the stream's
              `!fault` stream (requires @OnError(action='STREAM'))
      STORE   dead-letter the failed event to the ErrorStore for replay

    A mid-batch failure no longer discards the rest of the batch: every row
    is individually published, retried, routed, or dead-lettered, and the
    counts surface in statistics_report() (sink_retries / sink_dead_letters
    / sink_dropped)."""

    ON_ERROR_ACTIONS = ("LOG", "WAIT", "STREAM", "STORE")

    def init(self, stream_definition, options: dict, mapper: SinkMapper, ctx) -> None:
        self.definition = stream_definition
        self.options = options
        self.mapper = mapper
        self.ctx = ctx
        self.on_error = (options.get("on.error") or "LOG").upper()
        if self.on_error not in self.ON_ERROR_ACTIONS:
            raise SiddhiAppCreationError(
                f"@sink on.error must be one of {self.ON_ERROR_ACTIONS}, "
                f"got {self.on_error!r}")
        try:
            self.max_retries = int(options.get("max.retries", 5))
        except (TypeError, ValueError):
            raise SiddhiAppCreationError(
                f"@sink max.retries must be an int, "
                f"got {options.get('max.retries')!r}") from None
        self._retry_counter = BackoffRetryCounter()
        #: injectable for tests / fault harnesses (virtual clocks)
        self._sleep = time.sleep
        #: the stream junction this sink subscribes to (set by io/wiring.py;
        #: carries the `!fault` junction for on.error=STREAM routing)
        self._junction = None

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload) -> None:
        raise NotImplementedError

    # -- robust batch publication -------------------------------------------

    def _map_and_publish(self, row: tuple) -> None:
        self.publish(self.mapper.map(row))

    def publish_rows(self, rows: list[tuple], timestamps=None) -> None:
        """Publish a batch row-by-row under the sink's on.error policy.
        `timestamps` (parallel to rows) ride into dead-letter entries and
        fault-stream events; None falls back to the current time."""
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None and tele.on:
            t0 = time.perf_counter_ns()
            try:
                self._publish_rows(rows, timestamps)
            finally:
                tele.record_sink(self.definition.id, len(rows),
                                 time.perf_counter_ns() - t0)
        else:
            self._publish_rows(rows, timestamps)

    def _publish_rows(self, rows: list[tuple], timestamps=None) -> None:
        for i, row in enumerate(rows):
            try:
                self._map_and_publish(row)
            except ConnectionUnavailableException as e:
                if self.on_error == "WAIT":
                    if not self._retry_publish(row):
                        # reconnects exhausted: dead-letter the in-flight
                        # remainder (this row and everything after it)
                        self._dead_letter(rows[i:], timestamps, i, e)
                        return
                else:
                    self._handle_error(row, self._ts(timestamps, i), e)
            except Exception as e:  # noqa: BLE001 — policy decides
                self._handle_error(row, self._ts(timestamps, i), e)

    def _retry_publish(self, row: tuple) -> bool:
        """Reconnect-with-backoff loop for one row (reference:
        Sink.connectWithRetry / publish retry on connection loss). Bounded
        by max.retries; the reference retries forever on a scheduler."""
        counter = self._retry_counter
        for _attempt in range(self.max_retries):
            self.ctx.statistics.track_sink_retry(self.definition.id)
            self._sleep(counter.get_time_interval_ms() / 1000.0)
            counter.increment()
            try:
                self.disconnect()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
            try:
                self.connect()
                self._map_and_publish(row)
                counter.reset()
                return True
            except Exception:  # noqa: BLE001 — keep backing off
                continue
        return False

    def _ts(self, timestamps, i: int) -> int:
        if timestamps is not None and i < len(timestamps):
            return int(timestamps[i])
        return self.ctx.timestamp_generator.current_time()

    def _log_ctx(self) -> dict:
        """logging `extra` for sink error paths: app/stream plus the active
        batch-trace ID, so SIDDHI_LOG_FORMAT=json lines (and flight-recorder
        bundle log tails) correlate with frozen batch traces."""
        ctx = {"app": self.ctx.name, "stream": self.definition.id}
        tele = getattr(self.ctx, "telemetry", None)
        if tele is not None:
            tr = tele.active()
            if tr is not None:
                ctx["batch_id"] = tr.batch_id
        return ctx

    def _handle_error(self, row: tuple, ts: int, e: Exception) -> None:
        """One failed row under LOG / STREAM / STORE (WAIT handles
        connection loss before getting here and degrades to STORE for
        non-connection errors — never a silent drop)."""
        sid = self.definition.id
        action = self.on_error
        if action == "STREAM":
            fj = getattr(self._junction, "fault_junction", None)
            if fj is not None:
                fj.send_row(ts, tuple(row) + (str(e),))
                fj.flush()
                return
            log.error("@sink(on.error='STREAM') on %r but the stream has no "
                      "fault stream (add @OnError(action='STREAM')); "
                      "dead-lettering instead", sid, extra=self._log_ctx())
        if action in ("STREAM", "STORE", "WAIT"):
            store = getattr(self.ctx, "error_store", None)
            if store is not None:
                store.save(self.ctx.name, sid, [(ts, tuple(row))], str(e))
                self.ctx.statistics.track_dead_letter(sid, 1)
                self._note_dead_letter(1)
                return
            log.error("@sink(on.error=%r) on %r but no error store is "
                      "configured; logging instead", action, sid,
                      extra=self._log_ctx())
        self.ctx.statistics.track_sink_drop(sid, 1)
        log.exception("sink %r failed to publish event %r: %s", sid, row, e,
                      extra=self._log_ctx())

    def _note_dead_letter(self, n: int) -> None:
        """Feed the flight recorder's rolling dead-letter burst detector."""
        rec = getattr(self.ctx, "recorder", None)
        if rec is not None:
            rec.on_dead_letter(n)

    def _dead_letter(self, rows: list, timestamps, offset: int,
                     e: Exception) -> None:
        """Dead-letter a whole exhausted batch remainder as ONE ErrorStore
        entry (replayable via ErrorStore.replay)."""
        sid = self.definition.id
        events = [(self._ts(timestamps, offset + k), tuple(r))
                  for k, r in enumerate(rows)]
        store = getattr(self.ctx, "error_store", None)
        if store is not None:
            store.save(self.ctx.name, sid, events, str(e))
            self.ctx.statistics.track_dead_letter(sid, len(events))
            log.warning("sink %r: retries exhausted; dead-lettered %d "
                        "event(s) to the error store", sid, len(events),
                        extra=self._log_ctx())
            self._note_dead_letter(len(events))
            return
        self.ctx.statistics.track_sink_drop(sid, len(events))
        log.error("sink %r: retries exhausted and no error store configured; "
                  "dropped %d event(s): %s", sid, len(events), e,
                  extra=self._log_ctx())


class InMemorySink(Sink):
    """@sink(type='inMemory', topic='x') (reference: InMemorySink.java:64)."""

    def init(self, stream_definition, options, mapper, ctx) -> None:
        super().init(stream_definition, options, mapper, ctx)
        self.topic = options.get("topic")
        if not self.topic:
            raise SiddhiAppCreationError("inMemory sink needs topic=")

    def publish(self, payload) -> None:
        InMemoryBroker.publish(self.topic, payload)


class LogSink(Sink):
    """@sink(type='log') (reference: LogSink.java) — logs each event."""

    def init(self, stream_definition, options, mapper, ctx) -> None:
        super().init(stream_definition, options, mapper, ctx)
        self.prefix = options.get("prefix", f"{ctx.name}:{stream_definition.id}")

    def publish(self, payload) -> None:
        log.info("%s : %s", self.prefix, payload)


# --------------------------------------------------------------------------- #
# distributed transports
# --------------------------------------------------------------------------- #


class DistributionStrategy:
    """Reference: core/stream/output/sink/distributed/DistributionStrategy.java —
    picks destination indices per event."""

    def init(self, n_destinations: int, options: dict, stream_definition) -> None:
        self.n = n_destinations

    def destinations(self, row: tuple) -> list[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def init(self, n, options, stream_definition) -> None:
        super().init(n, options, stream_definition)
        self._i = 0

    def destinations(self, row):
        d = self._i % self.n
        self._i += 1
        return [d]


class PartitionedStrategy(DistributionStrategy):
    """@distribution(strategy='partitioned', partitionKey='attr')."""

    def init(self, n, options, stream_definition) -> None:
        super().init(n, options, stream_definition)
        key = options.get("partitionKey") or options.get("partition.key")
        if not key:
            raise SiddhiAppCreationError(
                "partitioned distribution needs partitionKey=")
        names = [a.name for a in stream_definition.attributes]
        if key not in names:
            raise SiddhiAppCreationError(f"partitionKey {key!r} not an attribute")
        self._idx = names.index(key)
        # stable across processes/restarts (built-in hash() is seeded per
        # process for str) — mirrors the reference's deterministic
        # String.hashCode() partitioning. The key is canonicalized through
        # the DECLARED attribute type so equal-comparing values alias
        # (1 vs 1.0 vs True; -0.0 vs 0.0). OBJECT attributes fall back to
        # hash(), which keeps equal keys together within a process.
        from ..query_api.definition import AttributeType as T

        atype = stream_definition.attributes[self._idx].type
        if atype in (T.FLOAT, T.DOUBLE):
            self._canon = lambda v: repr(float(v) + 0.0)  # folds -0.0 to 0.0
        elif atype in (T.INT, T.LONG):
            self._canon = lambda v: repr(int(v))
        elif atype is T.BOOL:
            self._canon = lambda v: repr(bool(v))
        elif atype is T.STRING:
            self._canon = str
        else:  # OBJECT — no value-deterministic serialization
            self._canon = None

    def destinations(self, row):
        v = row[self._idx]
        if v is None:  # deterministic for every attribute type, OBJECT too
            return [zlib.crc32(b"\0null") % self.n]
        if self._canon is None:
            return [hash(v) % self.n]
        return [zlib.crc32(self._canon(v).encode()) % self.n]


class BroadcastStrategy(DistributionStrategy):
    def destinations(self, row):
        return list(range(self.n))


class DistributedSink(Sink):
    """Fans one logical sink out across N destination sinks (reference:
    MultiClientDistributedSink / SingleClientDistributedSink +
    DistributedTransport)."""

    def init_distributed(self, destinations: list[Sink],
                         strategy: DistributionStrategy) -> None:
        self.destinations = destinations
        self.strategy = strategy

    def _map_and_publish(self, row: tuple) -> None:
        # retry/on.error handling rides the base publish_rows: a failing
        # destination surfaces here and the whole fan-out for the row is
        # retried after reconnect (destinations are idempotent transports
        # in the reference's multi-client model)
        payload, payload_mapper = None, None
        for d in self.strategy.destinations(row):
            sink = self.destinations[d]
            if sink.mapper is not payload_mapper:
                payload, payload_mapper = sink.mapper.map(row), sink.mapper
            sink.publish(payload)

    def connect(self) -> None:
        for d in self.destinations:
            d.connect()

    def disconnect(self) -> None:
        for d in self.destinations:
            d.disconnect()


def register_all() -> None:
    GLOBAL.register(ExtensionKind.SINK, "", "inMemory", InMemorySink)
    GLOBAL.register(ExtensionKind.SINK, "", "log", LogSink)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "passThrough", PassThroughSinkMapper)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "json", JsonSinkMapper)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "text", TextSinkMapper)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "roundRobin",
                    RoundRobinStrategy)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "partitioned",
                    PartitionedStrategy)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "broadcast",
                    BroadcastStrategy)


register_all()
