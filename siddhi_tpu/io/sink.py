"""Sinks, sink mappers & distributed transports — stream events out.

Reference: core/stream/output/sink/Sink.java:62 (publish + retry),
SinkMapper.java:44 (event → payload with {{attr}} templating —
core/util/transport/TemplateBuilder.java), InMemorySink.java:64, LogSink.java,
distributed/DistributedTransport.java + RoundRobin/Partitioned/Broadcast
DistributionStrategy (core/util/transport/), SinkHandler SPI.
"""

from __future__ import annotations

import json as _json
import logging
import re
import zlib
from typing import Optional

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from .broker import InMemoryBroker
from .source import BackoffRetryCounter, ConnectionUnavailableException

log = logging.getLogger("siddhi_tpu")


class SinkMapper:
    """Row → payload SPI (reference: SinkMapper.java:44)."""

    def init(self, stream_definition, options: dict,
             payload_template: Optional[str]) -> None:
        self.definition = stream_definition
        self.options = options
        self.payload_template = payload_template

    def map(self, row: tuple) -> object:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, row: tuple) -> object:
        return row


class JsonSinkMapper(SinkMapper):
    """@map(type='json') — {"event": {attr: value}}."""

    def map(self, row: tuple) -> object:
        ev = {a.name: v for a, v in zip(self.definition.attributes, row)}
        return _json.dumps({"event": ev})


_TEMPLATE_RE = re.compile(r"\{\{(\w+)\}\}")


class TextSinkMapper(SinkMapper):
    """@map(type='text', @payload('price is {{price}}')) — the reference's
    TemplateBuilder {{attr}} substitution (core/util/transport/TemplateBuilder.java)."""

    def map(self, row: tuple) -> object:
        values = {a.name: v for a, v in zip(self.definition.attributes, row)}
        if self.payload_template is None:
            return ", ".join(f"{k}:{v}" for k, v in values.items())
        return _TEMPLATE_RE.sub(lambda m: str(values[m.group(1)]),
                                self.payload_template)


class Sink:
    """Transport SPI (reference: Sink.java:62)."""

    def init(self, stream_definition, options: dict, mapper: SinkMapper, ctx) -> None:
        self.definition = stream_definition
        self.options = options
        self.mapper = mapper
        self.ctx = ctx

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload) -> None:
        raise NotImplementedError

    def publish_rows(self, rows: list[tuple]) -> None:
        for row in rows:
            self.publish(self.mapper.map(row))


class InMemorySink(Sink):
    """@sink(type='inMemory', topic='x') (reference: InMemorySink.java:64)."""

    def init(self, stream_definition, options, mapper, ctx) -> None:
        super().init(stream_definition, options, mapper, ctx)
        self.topic = options.get("topic")
        if not self.topic:
            raise SiddhiAppCreationError("inMemory sink needs topic=")

    def publish(self, payload) -> None:
        InMemoryBroker.publish(self.topic, payload)


class LogSink(Sink):
    """@sink(type='log') (reference: LogSink.java) — logs each event."""

    def init(self, stream_definition, options, mapper, ctx) -> None:
        super().init(stream_definition, options, mapper, ctx)
        self.prefix = options.get("prefix", f"{ctx.name}:{stream_definition.id}")

    def publish(self, payload) -> None:
        log.info("%s : %s", self.prefix, payload)


# --------------------------------------------------------------------------- #
# distributed transports
# --------------------------------------------------------------------------- #


class DistributionStrategy:
    """Reference: core/stream/output/sink/distributed/DistributionStrategy.java —
    picks destination indices per event."""

    def init(self, n_destinations: int, options: dict, stream_definition) -> None:
        self.n = n_destinations

    def destinations(self, row: tuple) -> list[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def init(self, n, options, stream_definition) -> None:
        super().init(n, options, stream_definition)
        self._i = 0

    def destinations(self, row):
        d = self._i % self.n
        self._i += 1
        return [d]


class PartitionedStrategy(DistributionStrategy):
    """@distribution(strategy='partitioned', partitionKey='attr')."""

    def init(self, n, options, stream_definition) -> None:
        super().init(n, options, stream_definition)
        key = options.get("partitionKey") or options.get("partition.key")
        if not key:
            raise SiddhiAppCreationError(
                "partitioned distribution needs partitionKey=")
        names = [a.name for a in stream_definition.attributes]
        if key not in names:
            raise SiddhiAppCreationError(f"partitionKey {key!r} not an attribute")
        self._idx = names.index(key)
        # stable across processes/restarts (built-in hash() is seeded per
        # process for str) — mirrors the reference's deterministic
        # String.hashCode() partitioning. The key is canonicalized through
        # the DECLARED attribute type so equal-comparing values alias
        # (1 vs 1.0 vs True; -0.0 vs 0.0). OBJECT attributes fall back to
        # hash(), which keeps equal keys together within a process.
        from ..query_api.definition import AttributeType as T

        atype = stream_definition.attributes[self._idx].type
        if atype in (T.FLOAT, T.DOUBLE):
            self._canon = lambda v: repr(float(v) + 0.0)  # folds -0.0 to 0.0
        elif atype in (T.INT, T.LONG):
            self._canon = lambda v: repr(int(v))
        elif atype is T.BOOL:
            self._canon = lambda v: repr(bool(v))
        elif atype is T.STRING:
            self._canon = str
        else:  # OBJECT — no value-deterministic serialization
            self._canon = None

    def destinations(self, row):
        v = row[self._idx]
        if v is None:  # deterministic for every attribute type, OBJECT too
            return [zlib.crc32(b"\0null") % self.n]
        if self._canon is None:
            return [hash(v) % self.n]
        return [zlib.crc32(self._canon(v).encode()) % self.n]


class BroadcastStrategy(DistributionStrategy):
    def destinations(self, row):
        return list(range(self.n))


class DistributedSink(Sink):
    """Fans one logical sink out across N destination sinks (reference:
    MultiClientDistributedSink / SingleClientDistributedSink +
    DistributedTransport)."""

    def init_distributed(self, destinations: list[Sink],
                         strategy: DistributionStrategy) -> None:
        self.destinations = destinations
        self.strategy = strategy

    def publish_rows(self, rows: list[tuple]) -> None:
        for row in rows:
            payload, payload_mapper = None, None
            for d in self.strategy.destinations(row):
                sink = self.destinations[d]
                if sink.mapper is not payload_mapper:
                    payload, payload_mapper = sink.mapper.map(row), sink.mapper
                sink.publish(payload)

    def connect(self) -> None:
        for d in self.destinations:
            d.connect()

    def disconnect(self) -> None:
        for d in self.destinations:
            d.disconnect()


def register_all() -> None:
    GLOBAL.register(ExtensionKind.SINK, "", "inMemory", InMemorySink)
    GLOBAL.register(ExtensionKind.SINK, "", "log", LogSink)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "passThrough", PassThroughSinkMapper)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "json", JsonSinkMapper)
    GLOBAL.register(ExtensionKind.SINK_MAPPER, "", "text", TextSinkMapper)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "roundRobin",
                    RoundRobinStrategy)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "partitioned",
                    PartitionedStrategy)
    GLOBAL.register(ExtensionKind.DISTRIBUTION_STRATEGY, "", "broadcast",
                    BroadcastStrategy)


register_all()
