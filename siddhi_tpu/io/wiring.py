"""@source/@sink annotation wiring — instantiate transports + mappers per
stream definition.

Reference: core/util/parser/helper/DefinitionParserHelper.java —
addEventSource:310 / addEventSink:435 read @source/@sink annotations, resolve
the transport + @map mapper (+ @attributes/@payload, @distribution with
@destination endpoints) from the extension registry and bind them to the
stream junction.
"""

from __future__ import annotations

from typing import Optional

from ..errors import SiddhiAppCreationError
from ..extension.registry import ExtensionKind
from ..query_api.annotation import Annotation
from .sink import DistributedSink, Sink, SinkMapper
from .source import Source, SourceMapper


def _options(ann: Annotation) -> dict:
    return {e.key: e.value for e in ann.elements if e.key}


def _attribute_mappings(map_ann: Annotation, definition):
    attrs_ann = map_ann.nested_annotation("attributes")
    if attrs_ann is None:
        return None
    keyed = [(e.key, e.value) for e in attrs_ann.elements if e.key]
    if keyed:
        by_name = dict(keyed)
        missing = [a.name for a in definition.attributes
                   if a.name not in by_name]
        if missing:
            raise SiddhiAppCreationError(
                f"@attributes mapping for {definition.id!r} missing: {missing}")
        return [(a.name, by_name[a.name]) for a in definition.attributes]
    # positional form: @attributes('$.a', '$.b') in schema order
    return [(a.name, e.value)
            for a, e in zip(definition.attributes, attrs_ann.elements)]


def _make_source_mapper(map_ann: Optional[Annotation], definition,
                        registry) -> SourceMapper:
    mtype = "passThrough"
    options: dict = {}
    mappings = None
    if map_ann is not None:
        options = _options(map_ann)
        mtype = options.pop("type", "passThrough")
        mappings = _attribute_mappings(map_ann, definition)
    cls = registry.require(ExtensionKind.SOURCE_MAPPER, "", mtype)
    mapper = cls()
    mapper.init(definition, options, mappings)
    return mapper


def _make_sink_mapper(map_ann: Optional[Annotation], definition,
                      registry) -> SinkMapper:
    mtype = "passThrough"
    options: dict = {}
    template = None
    if map_ann is not None:
        options = _options(map_ann)
        mtype = options.pop("type", "passThrough")
        payload_ann = map_ann.nested_annotation("payload")
        if payload_ann is not None and payload_ann.elements:
            template = payload_ann.elements[0].value
    cls = registry.require(ExtensionKind.SINK_MAPPER, "", mtype)
    mapper = cls()
    mapper.init(definition, options, template)
    return mapper


def _config_defaults(ctx, namespace: str, name: str) -> dict:
    """Deployment-config properties for one extension (annotation options
    override them — reference: per-extension ConfigReader precedence)."""
    cm = getattr(ctx, "config_manager", None)
    if cm is None:
        return {}
    return cm.generate_config_reader(namespace, name).get_all_configs()


def build_source(ann: Annotation, junction, ctx) -> Source:
    """One @source(...) annotation → connected-on-start Source bound to the
    stream's junction staging buffers."""
    options = _options(ann)
    stype = options.pop("type", None)
    if not stype:
        raise SiddhiAppCreationError("@source needs type=")
    options = {**_config_defaults(ctx, "source", stype), **options}
    definition = junction.definition
    registry = ctx.registry
    mapper = _make_source_mapper(ann.nested_annotation("map"), definition,
                                 registry)
    cls = registry.require(ExtensionKind.SOURCE, "", stype)
    source = cls()

    def handler(rows: list[tuple]) -> None:
        now = ctx.timestamp_generator.current_time()
        for row in rows:
            junction.send_row(now, row)
        # push semantics like the reference's synchronous inMemory delivery;
        # high-rate transports amortize via the junction's batch threshold.
        # Bounded (drop/fault-policy) junctions skip the per-payload flush:
        # delivery there is pull-driven (feeder/auto-flush) so the staging
        # bound — not the transport's push rate — paces the pipeline.
        if not junction._bounded_mode():
            junction.flush(now)

    source.init(definition, options, mapper, handler, ctx)
    # backpressure wiring: the junction pauses/resumes its attached sources
    # on watermark crossings (Source.pause/resume, reference :113-153)
    junction.attached_sources.append(source)
    return source


def build_sink(ann: Annotation, junction, ctx) -> Sink:
    """One @sink(...) annotation → Sink subscribed to the stream junction."""
    options = _options(ann)
    stype = options.pop("type", None)
    if not stype:
        raise SiddhiAppCreationError("@sink needs type=")
    options = {**_config_defaults(ctx, "sink", stype), **options}
    definition = junction.definition
    registry = ctx.registry
    mapper = _make_sink_mapper(ann.nested_annotation("map"), definition, registry)

    dist_ann = ann.nested_annotation("distribution")
    if dist_ann is not None:
        # @distribution(strategy='...', @destination(topic='t1'), ...)
        dopts = _options(dist_ann)
        strategy_name = dopts.pop("strategy", "roundRobin")
        strat_cls = registry.require(ExtensionKind.DISTRIBUTION_STRATEGY, "",
                                     strategy_name)
        dests = []
        for dest_ann in dist_ann.nested:
            if dest_ann.name.lower() != "destination":
                continue
            dest_opts = dict(options)
            dest_opts.update(_options(dest_ann))
            cls = registry.require(ExtensionKind.SINK, "", stype)
            d = cls()
            d.init(definition, dest_opts, mapper, ctx)
            dests.append(d)
        if not dests:
            raise SiddhiAppCreationError("@distribution needs @destination(...)s")
        strategy = strat_cls()
        strategy.init(len(dests), dopts, definition)
        sink = DistributedSink()
        sink.init(definition, options, mapper, ctx)
        sink.init_distributed(dests, strategy)
    else:
        cls = registry.require(ExtensionKind.SINK, "", stype)
        sink = cls()
        sink.init(definition, options, mapper, ctx)

    from ..core.stream import StreamCallback

    # fault routing / dead-letter entries need the stream's junction and
    # the events' original timestamps (Sink.publish_rows on.error policies)
    sink._junction = junction

    class _SinkCallback(StreamCallback):
        # sink-owned subscription: the blue-green upgrade migrates USER
        # callbacks to the v2 junctions but leaves sink callbacks with
        # their runtime (v2 builds + connects its own sinks)
        _is_sink = True

        def receive(self, events) -> None:
            sink.publish_rows([tuple(e.data) for e in events],
                              timestamps=[e.timestamp for e in events])

    junction.subscribe(_SinkCallback())
    return sink
