"""In-memory topic broker — the zero-I/O transport fabric used by the inMemory
source/sink pair and the behavioral test harness.

Reference: core/util/transport/InMemoryBroker.java:29 — a static topic →
subscribers map with publish/subscribe. Kept process-global exactly like the
reference so separate SiddhiManager instances exchange messages in tests.
"""

from __future__ import annotations

from typing import Callable

from ..util.locks import named_lock


class Subscriber:
    """Reference: InMemoryBroker.Subscriber — onMessage + topic."""

    def on_message(self, msg) -> None:
        raise NotImplementedError

    def get_topic(self) -> str:
        raise NotImplementedError


class _FnSubscriber(Subscriber):
    def __init__(self, topic: str, fn: Callable):
        self._topic = topic
        self._fn = fn

    def on_message(self, msg) -> None:
        self._fn(msg)

    def get_topic(self) -> str:
        return self._topic


class InMemoryBroker:
    """Static pub/sub hub (all methods class-level, like the reference)."""

    _topics: dict[str, list[Subscriber]] = {}
    _lock = named_lock("broker.registry")

    @classmethod
    def subscribe(cls, subscriber: Subscriber) -> None:
        with cls._lock:
            cls._topics.setdefault(subscriber.get_topic(), []).append(subscriber)

    @classmethod
    def subscribe_fn(cls, topic: str, fn: Callable) -> Subscriber:
        sub = _FnSubscriber(topic, fn)
        cls.subscribe(sub)
        return sub

    @classmethod
    def unsubscribe(cls, subscriber: Subscriber) -> None:
        with cls._lock:
            subs = cls._topics.get(subscriber.get_topic(), [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, msg) -> None:
        # snapshot the subscriber list UNDER the lock: a concurrent
        # subscribe/unsubscribe mutates the same list, and an unlocked
        # list() copy can race the mutation mid-iteration. Delivery happens
        # outside the lock — a slow (or paused/backpressured) subscriber
        # must not serialize every other topic's publishes.
        with cls._lock:
            subs = tuple(cls._topics.get(topic, ()))
        for sub in subs:
            sub.on_message(msg)

    @classmethod
    def clear(cls) -> None:
        """Test helper: drop all subscriptions."""
        with cls._lock:
            cls._topics.clear()
