"""Record-table SPI + `@cache` — the extension seam for external stores.

Reference: core/table/record/AbstractRecordTable.java and
AbstractQueryableRecordTable.java:99 (1,133 LoC) — RDBMS/Mongo-style stores
plug in by compiling conditions through an ExpressionVisitor walk and
implementing add/find/update/delete against the backend; an optional
in-memory cache (`@cache(size=..., policy=FIFO|LRU|LFU)` —
CacheTable.java + CacheTableFIFO/LRU/LFU) absorbs reads.

TPU division of labour:

- the STORE is a host-side adapter (network/disk I/O never belongs on
  device): `RecordStore` SPI registered under `@store(type='name')` via
  `ExtensionKind.STORE`;
- conditions reach the store through `StoreConditionVisitor` — the same
  compile-once visitor-walk contract as the reference, so a SQL store can
  emit a WHERE clause; `PredicateVisitor` is the built-in fallback that
  compiles to a Python row predicate;
- the CACHE is a real device table (core/table.py InMemoryTable): joins and
  `in Table` probes run against it INSIDE the jitted step at device speed —
  the reference's cacheEnabled read path. Cache content is mastered by a
  host-side policy map (FIFO/LRU/LFU) and mirrored to the device table on
  change. Recency/frequency update on host-path reads and writes; in-kernel
  probes cannot touch host metadata (documented divergence).
- on-demand finds are authoritative against the store (reference:
  AbstractQueryableRecordTable.find) and read-through refresh the cache.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterable, Optional

import numpy as np

from ..errors import SiddhiAppCreationError, SiddhiError
from ..query_api.definition import AttributeType, TableDefinition
from ..query_api.expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    IsNull,
    MathExpression,
    Not,
    Or,
    Variable,
)

# --------------------------------------------------------------- visitor SPI


class StoreConditionVisitor:
    """Compile-time walk of an ON condition (reference:
    core/util/collection/expression ExpressionVisitor contract). Stores
    override to build native query syntax; every method receives plain AST
    leaves."""

    def begin_and(self): ...
    def end_and(self): ...
    def begin_or(self): ...
    def end_or(self): ...
    def begin_not(self): ...
    def end_not(self): ...
    def begin_compare(self, op: CompareOp): ...
    def end_compare(self, op: CompareOp): ...
    def visit_constant(self, value, type_name: Optional[str]): ...
    def visit_attribute(self, name: str): ...
    def visit_stream_value(self, name: str):
        """A value from the probing stream (parameterized at lookup time)."""

    def visit_is_null(self, name: str): ...

    def result(self):
        raise NotImplementedError


def walk_condition(expr: Optional[Expression], visitor: StoreConditionVisitor,
                   table_id: str):
    """Drive a visitor over the condition AST. Attributes of the table visit
    as visit_attribute; everything else (stream references) as
    visit_stream_value placeholders."""
    if expr is None:
        return visitor.result()

    def walk(e: Expression):
        if isinstance(e, And):
            visitor.begin_and()
            walk(e.left)
            walk(e.right)
            visitor.end_and()
        elif isinstance(e, Or):
            visitor.begin_or()
            walk(e.left)
            walk(e.right)
            visitor.end_or()
        elif isinstance(e, Not):
            visitor.begin_not()
            walk(e.expression)
            visitor.end_not()
        elif isinstance(e, Compare):
            visitor.begin_compare(e.op)
            walk(e.left)
            walk(e.right)
            visitor.end_compare(e.op)
        elif isinstance(e, IsNull):
            if isinstance(e.expression, Variable):
                visitor.visit_is_null(e.expression.attribute)
            else:
                raise SiddhiAppCreationError(
                    "record-store isNull supports attribute operands only")
        elif isinstance(e, Constant):
            visitor.visit_constant(e.value, e.type_name)
        elif isinstance(e, Variable):
            if e.stream_id in (None, table_id):
                visitor.visit_attribute(e.attribute)
            else:
                visitor.visit_stream_value(f"{e.stream_id}.{e.attribute}")
        else:
            raise SiddhiAppCreationError(
                f"record-store conditions do not support "
                f"{type(e).__name__} (math/functions evaluate on device "
                "tables only)")

    walk(expr)
    return visitor.result()


class PredicateVisitor(StoreConditionVisitor):
    """Fallback compiler: condition -> Python predicate over row dicts.
    Used by InMemoryRecordStore and any adapter without native pushdown."""

    _OPS = {
        CompareOp.EQUAL: lambda a, b: a == b,
        CompareOp.NOT_EQUAL: lambda a, b: a != b,
        CompareOp.GREATER_THAN: lambda a, b: a > b,
        CompareOp.GREATER_THAN_EQUAL: lambda a, b: a >= b,
        CompareOp.LESS_THAN: lambda a, b: a < b,
        CompareOp.LESS_THAN_EQUAL: lambda a, b: a <= b,
    }

    def __init__(self):
        self._stack: list = []

    def begin_and(self): pass

    def end_and(self):
        r, l = self._stack.pop(), self._stack.pop()
        self._stack.append(lambda row, l=l, r=r: l(row) and r(row))

    def begin_or(self): pass

    def end_or(self):
        r, l = self._stack.pop(), self._stack.pop()
        self._stack.append(lambda row, l=l, r=r: l(row) or r(row))

    def begin_not(self): pass

    def end_not(self):
        e = self._stack.pop()
        self._stack.append(lambda row, e=e: not e(row))

    def begin_compare(self, op): pass

    def end_compare(self, op):
        rhs, lhs = self._stack.pop(), self._stack.pop()
        fn = self._OPS[op]
        self._stack.append(
            lambda row, lhs=lhs, rhs=rhs, fn=fn: fn(lhs(row), rhs(row)))

    def visit_constant(self, value, type_name):
        self._stack.append(lambda row, v=value: v)

    def visit_attribute(self, name):
        self._stack.append(lambda row, n=name: row.get(n))

    def visit_stream_value(self, name):
        raise SiddhiAppCreationError(
            "record-store on-demand conditions cannot reference stream "
            "attributes; join record tables through their @cache instead")

    def visit_is_null(self, name):
        self._stack.append(lambda row, n=name: row.get(n) is None)

    def result(self) -> Callable:
        if not self._stack:
            return lambda row: True
        assert len(self._stack) == 1
        return self._stack[0]


class ParamPredicateVisitor(PredicateVisitor):
    """PredicateVisitor variant whose compiled form takes (row, params):
    stream-value placeholders (`probe.attr`) read from `params` at call
    time. Backs the GENERAL cache-miss store fallback — computed-key and
    non-equi probe conditions (reference:
    AbstractQueryableRecordTable.java:207-238 compiles every condition
    against the store with streamVariable parameters)."""

    def end_and(self):
        r, l = self._stack.pop(), self._stack.pop()
        self._stack.append(lambda row, p, l=l, r=r: l(row, p) and r(row, p))

    def end_or(self):
        r, l = self._stack.pop(), self._stack.pop()
        self._stack.append(lambda row, p, l=l, r=r: l(row, p) or r(row, p))

    def end_not(self):
        e = self._stack.pop()
        self._stack.append(lambda row, p, e=e: not e(row, p))

    def end_compare(self, op):
        rhs, lhs = self._stack.pop(), self._stack.pop()
        fn = self._OPS[op]

        def cmp(row, p, lhs=lhs, rhs=rhs, fn=fn):
            a, b = lhs(row, p), rhs(row, p)
            if a is None or b is None:
                return False
            return fn(a, b)

        self._stack.append(cmp)

    def visit_constant(self, value, type_name):
        self._stack.append(lambda row, p, v=value: v)

    def visit_attribute(self, name):
        self._stack.append(lambda row, p, n=name: row.get(n))

    def visit_stream_value(self, name):
        self._stack.append(lambda row, p, n=name: p.get(n))

    def visit_is_null(self, name):
        self._stack.append(lambda row, p, n=name: row.get(n) is None)

    def result(self) -> Callable:
        if not self._stack:
            return lambda row, p: True
        assert len(self._stack) == 1
        return self._stack[0]


# ------------------------------------------------------------------ store SPI


class RecordStore:
    """External-store adapter SPI (reference: AbstractRecordTable). One
    instance per `define table ... @store(type='x', key='val', ...)`.

    Rows cross the SPI as plain dicts keyed by attribute name (decoded host
    values, strings as str). `compile_condition` may return any handle the
    adapter's find/delete/update understand."""

    def init(self, definition: TableDefinition, properties: dict,
             config_reader=None) -> None:
        self.definition = definition
        self.properties = properties

    def connect(self) -> None: ...
    def disconnect(self) -> None: ...

    def compile_condition(self, expr: Optional[Expression], table_id: str):
        return walk_condition(expr, PredicateVisitor(), table_id)

    def add(self, rows: list[dict]) -> None:
        raise NotImplementedError

    def find(self, compiled) -> Iterable[dict]:
        raise NotImplementedError

    def delete(self, compiled) -> int:
        raise NotImplementedError

    def update(self, compiled, updater: Callable[[dict], dict]) -> int:
        raise NotImplementedError

    def update_or_add(self, compiled, updater: Callable[[dict], dict],
                      rows: list[dict]) -> int:
        n = self.update(compiled, updater)
        if n == 0:
            self.add(rows)
        return n


class InMemoryRecordStore(RecordStore):
    """Reference-shaped demo adapter (the role of the reference's test
    stores): list-of-dicts backend, predicate-compiled conditions."""

    def init(self, definition, properties, config_reader=None):
        super().init(definition, properties, config_reader)
        self.rows: list[dict] = []

    def add(self, rows):
        self.rows.extend(dict(r) for r in rows)

    def find(self, compiled):
        return [dict(r) for r in self.rows if compiled(r)]

    def delete(self, compiled):
        before = len(self.rows)
        self.rows = [r for r in self.rows if not compiled(r)]
        return before - len(self.rows)

    def update(self, compiled, updater):
        n = 0
        for i, r in enumerate(self.rows):
            if compiled(r):
                self.rows[i] = updater(dict(r))
                n += 1
        return n


# -------------------------------------------------------------------- cache


class CachePolicy:
    """Host-side key → row bookkeeping for the device cache (reference:
    CacheTableFIFO/LRU/LFU)."""

    def __init__(self, size: int, policy: str):
        policy = policy.upper()
        if policy not in ("FIFO", "LRU", "LFU"):
            raise SiddhiAppCreationError(
                f"@cache policy must be FIFO, LRU or LFU, got {policy!r}")
        if size < 1:
            raise SiddhiAppCreationError(
                f"@cache size must be >= 1, got {size} — use "
                "@cache(size='N', policy='FIFO|LRU|LFU')")
        self.size = size
        self.policy = policy
        self.rows: OrderedDict = OrderedDict()  # key -> row dict
        self.freq: dict = {}
        #: True once the backing store has held more rows than the cache —
        #: joins and `in` probes read ONLY the cache, so evicted rows miss
        self.overflowed = False
        #: lifetime eviction counter: read-through warm memos (join
        #: condition fallback) are valid only while the rows they loaded
        #: stay resident — any eviction invalidates them
        self.evictions = 0

    def _evict_one(self, protected=frozenset()):
        # `protected` holds the current probing batch's working set: keys a
        # read-through warm must NOT evict, or the very probe that triggered
        # the warm would miss them (see ensure_cached_for_keys). Falls back
        # to normal policy order if everything is protected (working set >
        # cache size — separately warned).
        if self.policy == "LFU":
            pool = [k for k in self.rows if k not in protected] or \
                list(self.rows)
            victim = min(pool, key=lambda k: self.freq.get(k, 0))
        else:  # FIFO and LRU both evict the head of the ordering
            victim = next((k for k in self.rows if k not in protected),
                          None)
            if victim is None:
                victim = next(iter(self.rows))
        del self.rows[victim]
        self.freq.pop(victim, None)
        self.evictions += 1

    def put(self, key, row, protected=frozenset()) -> None:
        if key in self.rows:
            self.rows[key] = row
            if self.policy == "LRU":
                self.rows.move_to_end(key)
            self.freq[key] = self.freq.get(key, 0) + 1
            return
        while len(self.rows) >= self.size:
            self._evict_one(protected)
            self.overflowed = True
        self.rows[key] = row
        self.freq[key] = 1

    def touch(self, key) -> None:
        if key not in self.rows:
            return
        if self.policy == "LRU":
            self.rows.move_to_end(key)
        self.freq[key] = self.freq.get(key, 0) + 1

    def remove_if(self, pred) -> None:
        for k in [k for k, r in self.rows.items() if pred(r)]:
            del self.rows[k]
            self.freq.pop(k, None)

    def values(self) -> list[dict]:
        return list(self.rows.values())

    def clear(self) -> None:
        """Conservative full invalidation (pushdown conditions we cannot
        evaluate host-side)."""
        self.rows.clear()
        self.freq.clear()
        self.evictions += 1


# ----------------------------------------------------------------- runtime


class RecordTableRuntime:
    """Host runtime for a `@store(...)` table, presenting the same surface as
    core/table.py InMemoryTable so the rest of the engine (joins, `in`
    probes, on-demand queries, table CRUD outputs) composes unchanged.

    With `@cache`: `state` is the device cache table's state — in-kernel
    probes (joins, `in Table`) read it at device speed. Without a cache,
    in-kernel probes are rejected at plan time (the reference falls back to
    per-event store round trips there; a per-lane host call inside a jitted
    step has no TPU analogue).
    """

    def __init__(self, definition: TableDefinition, ctx, registry) -> None:
        from ..core.event import StreamCodec
        from ..core.table import InMemoryTable

        self.definition = definition
        self.ctx = ctx
        self.codec = StreamCodec(definition, ctx.global_strings)
        self.attr_types = {a.name: a.type for a in definition.attributes
                           if a.type != AttributeType.OBJECT}
        self._attr_names = [a.name for a in definition.attributes]

        store_ann = (definition.annotation("store")
                     or definition.annotation("Store"))
        props = {e.key: e.value for e in store_ann.elements if e.key}
        store_type = props.pop("type", None)
        if not store_type:
            raise SiddhiAppCreationError(
                f"table {definition.id!r}: @store needs type='...'")
        from ..extension.registry import ExtensionKind
        factory = registry.require(ExtensionKind.STORE, "", store_type)
        self.store: RecordStore = factory() if isinstance(factory, type) \
            else factory
        if not isinstance(self.store, RecordStore):
            raise SiddhiAppCreationError(
                f"store extension {store_type!r} must be a RecordStore")
        self.store.init(definition, props,
                        ctx.config_reader(f"store:{store_type}")
                        if hasattr(ctx, "config_reader") else None)
        self.store.connect()

        pk = definition.annotation("PrimaryKey") if definition.annotations \
            else None
        self.primary_keys = tuple(e.value for e in pk.elements) \
            if pk is not None else ()

        cache_ann = (definition.annotation("cache")
                     or definition.annotation("Cache"))
        self.cache = None
        self.cache_policy = None
        #: set by join/`in`-probe planners: enables the evicted-rows-miss
        #: warning when the store outgrows the cache
        self._used_in_probe = False
        self._probe_miss_warned = False
        #: set by probing runtimes that registered a host read-through
        #: (ensure_cached_for_keys) — softens the overflow warning from
        #: "wrong answers" to "slow path"
        self._probe_fallback_ready = False
        #: set when ANY probing runtime could NOT register a read-through
        #: (computed-key / non-equi probes): the hard miss warning must fire
        #: even if another runtime did register one
        self._probe_nofallback = False
        #: keys proven absent from the store — skips repeat store scans in
        #: the overflow slow path; invalidated by every store write
        self._absent_probe_keys: set = set()
        #: store mutation counter: read-through warm memos (join condition
        #: fallback — JoinQueryRuntime._condition_fallback) are valid only
        #: while the backing store is unchanged; bumped by every path that
        #: can ADD or REWRITE store rows
        self._store_rev = 0
        if cache_ann is not None:
            copts = {e.key: e.value for e in cache_ann.elements if e.key}
            size = int(copts.get("size", copts.get("max.size", 128)))
            policy = copts.get("policy", "FIFO")
            self.cache_policy = CachePolicy(size, policy)
            self.cache = InMemoryTable(definition, ctx, capacity=size)
        self.capacity = self.cache.capacity if self.cache else 0
        self.dropped_duplicates = 0

    # --- device surface (cache-backed) -----------------------------------

    @property
    def state(self):
        if self.cache is None:
            raise SiddhiAppCreationError(
                f"record table {self.definition.id!r} has no @cache: joins "
                "and `in` probes need @cache(size='N', policy='FIFO|LRU|LFU')")
        return self.cache.state

    def find_mask(self, cond, scope):
        return self.cache_table().find_mask(cond, scope)

    def contains_probe(self, scope, inner, eq_plan=None):
        return self.cache_table().contains_probe(scope, inner, eq_plan)

    def cache_table(self):
        if self.cache is None:
            # raise with the @cache guidance
            _ = self.state
        return self.cache

    def probe_indexes(self) -> dict:
        """Record tables probe through their device cache; index-aware `in`
        plans read the cache's sorted copies."""
        if self.cache is None:
            return {}
        return self.cache.probe_indexes()

    # --- host row plumbing -------------------------------------------------

    def _key(self, row: dict):
        if self.primary_keys:
            return tuple(row[k] for k in self.primary_keys)
        return tuple(row.get(n) for n in self._attr_names)

    def _rebuild_cache(self) -> None:
        if self.cache is None:
            return
        # reuse the one device table + its jitted insert (a fresh
        # InMemoryTable per rebuild would retrace/recompile every write)
        self.cache.clear()
        rows = [tuple(r.get(n) for n in self._attr_names)
                for r in self.cache_policy.values()]
        if rows:
            self.cache.insert_rows(rows)

    def _cache_put_rows(self, rows: list[dict]) -> None:
        if self.cache_policy is None:
            return
        for r in rows:
            self.cache_policy.put(self._key(r), r)
        if (self.cache_policy.overflowed and self._used_in_probe
                and not self._probe_miss_warned):
            self._probe_miss_warned = True
            import warnings
            if self._probe_fallback_ready and not self._probe_nofallback:
                # correctness preserved: probing runtimes pre-warm the cache
                # from the store per batch (ensure_cached_for_keys) — the
                # reference's cache-miss fallback
                # (AbstractQueryableRecordTable.java:207-238) — but each
                # probing batch may now pay a host store read
                warnings.warn(
                    f"@store table {self.definition.id!r}: the backing store "
                    f"exceeded @cache(size='{self.cache_policy.size}') — "
                    "probes stay correct via per-batch store read-through; "
                    "raise the cache size to stay on the device fast path",
                    stacklevel=2)
            else:
                # no fallback possible (non-equi / computed-key probe):
                # evicted rows MISS probes (documented, PARITY.md)
                warnings.warn(
                    f"@store table {self.definition.id!r}: the backing store "
                    f"exceeded @cache(size='{self.cache_policy.size}') and "
                    "the table is probed without store-fallback-capable "
                    "equi keys — evicted rows will MISS those probes; raise "
                    "the cache size to cover the store",
                    stacklevel=2)
        self._rebuild_cache()

    def ensure_cached_for_keys(self, attr_names: tuple, keys: set) -> bool:
        """Read-through for in-kernel probes — the TPU shape of the
        reference's cache-miss store fallback
        (AbstractQueryableRecordTable.java:109,207-238). A probing runtime
        calls this BEFORE its jitted step with the batch's distinct join-key
        tuples (projected on `attr_names`); every store row matching a key
        that is not cache-resident is loaded into the cache (and the device
        table rebuilt), so the device probe sees exactly what a store
        fallback would have returned. Returns True when the device cache
        changed. Keys proven absent are memoized until the next store write
        so steady-state probing of absent keys stays scan-free."""
        if self.cache_policy is None or not keys:
            return False

        def norm(row):
            # probe keys arrive round-tripped through DEVICE dtypes (f32
            # floats); store rows hold full-precision host values — compare
            # both sides in device space or evicted FLOAT-keyed rows would
            # never match (and be falsely memoized absent)
            out = []
            for a in attr_names:
                v = row.get(a)
                dt = self.codec.np_dtypes.get(a)
                if v is not None and dt is not None and dt.kind == "f":
                    v = float(dt.type(v))
                out.append(v)
            return tuple(out)

        # "key cached => fully cached" only holds when the key tuple
        # identifies at most ONE store row (primary key subset of the join
        # attrs); with duplicate-key stores, a cached row must not mask its
        # evicted siblings — scan for every non-absent probe key instead
        unique_per_key = bool(self.primary_keys) and \
            set(self.primary_keys) <= set(attr_names)
        if unique_per_key:
            have = {norm(r) for r in self.cache_policy.rows.values()}
            candidates = keys - have
        else:
            candidates = set(keys)
        # negative memo only for the in-process store: external backends can
        # gain rows out-of-band, so they re-scan per probing batch (the
        # reference re-queries the store on every cache miss)
        memo_ok = type(self.store).__module__.startswith("siddhi_tpu.") and \
            isinstance(self.store, InMemoryRecordStore)
        if memo_ok:
            candidates = {k for k in candidates
                          if (attr_names, k) not in self._absent_probe_keys}
        if not candidates:
            return False
        match_all = self.compile_condition(None)
        found = [r for r in self.store.find(match_all)
                 if norm(r) in candidates]
        found_keys = {norm(r) for r in found}
        if memo_ok:
            for k in candidates - found_keys:
                self._absent_probe_keys.add((attr_names, k))
            if len(self._absent_probe_keys) > (1 << 20):  # bounded memo
                self._absent_probe_keys.clear()
        if not found:
            return False
        # the batch's full store-present working set — BOTH already-resident
        # probe rows and the freshly loaded ones — must survive the warm:
        # putting row 'a' must not evict probe key 'b' of the same batch
        # (e.g. size-2 FIFO {b,c}, batch probes {a,b}) or the device probe
        # silently misses it despite the read-through
        resident_probe = {self._key(r)
                          for r in self.cache_policy.rows.values()
                          if norm(r) in keys}
        protected = resident_probe | {self._key(r) for r in found}
        if len(protected) > self.cache_policy.size:
            import warnings
            warnings.warn(
                f"@store table {self.definition.id!r}: one probing batch "
                f"needs {len(protected)} rows but "
                f"@cache(size='{self.cache_policy.size}') holds fewer — "
                "rows evicted mid-warm may still miss; raise the cache size "
                "above the per-batch distinct-key working set",
                stacklevel=2)
        for k in resident_probe:  # refresh recency so LRU keeps them too
            self.cache_policy.touch(k)
        changed = any(self._key(r) not in self.cache_policy.rows
                      or self.cache_policy.rows[self._key(r)] != r
                      for r in found)
        for r in found:
            self.cache_policy.put(self._key(r), r, protected=protected)
        if changed:
            self._rebuild_cache()
        return changed

    def compile_param_condition(self, expr):
        """Compile a probe condition with stream-value placeholders into
        fn(row, params) — the general (computed-key / non-equi) store
        fallback plan. Raises SiddhiAppCreationError for shapes the store
        walk cannot express (callers then document the cache-only miss)."""
        visitor = ParamPredicateVisitor()
        return walk_condition(expr, visitor, self.definition.id)

    def ensure_cached_for_condition(self, pred, param_rows: list) -> bool:
        """General read-through for in-kernel probes whose condition is not
        a simple equi key (`f(S.k) == T.k`, `S.k < T.k`): load every store
        row matching ANY of the batch's probe parameter rows into the
        cache, so the device probe sees exactly what a store fallback would
        return (reference: AbstractQueryableRecordTable.java:207-238).
        Cost: one host scan of the store × the batch's DISTINCT probe rows
        — bounded by batch size; the equi-key path (ensure_cached_for_keys)
        stays the fast path. Returns True when the device cache changed."""
        if self.cache_policy is None or not param_rows:
            return False

        def dev_norm(row):
            # store rows hold full-precision host values; probe params are
            # device-roundtripped (f32) — evaluate the predicate with BOTH
            # sides in device space or float comparisons never line up
            # (same rule as ensure_cached_for_keys' norm())
            out = {}
            for k, v in row.items():
                dt = self.codec.np_dtypes.get(k)
                if v is not None and dt is not None and dt.kind == "f":
                    v = float(dt.type(v))
                out[k] = v
            return out

        match_all = self.compile_condition(None)
        found = [r for r in self.store.find(match_all)
                 if any(pred(dev_norm(r), p) for p in param_rows)]
        if not found:
            return False
        protected = {self._key(r) for r in found}
        if len(protected) > self.cache_policy.size:
            import warnings
            warnings.warn(
                f"@store table {self.definition.id!r}: one probing batch's "
                f"condition matches {len(protected)} rows but "
                f"@cache(size='{self.cache_policy.size}') holds fewer — "
                "rows evicted mid-warm may still miss; raise the cache "
                "size above the per-batch matching working set",
                stacklevel=2)
        changed = any(self._key(r) not in self.cache_policy.rows
                      or self.cache_policy.rows[self._key(r)] != r
                      for r in found)
        for r in found:
            self.cache_policy.put(self._key(r), r, protected=protected)
        if changed:
            self._rebuild_cache()
        return changed

    def _batch_rows(self, batch) -> list[dict]:
        events = batch.to_host_events(self.codec)
        return [dict(zip(self._attr_names, e.data)) for e in events]

    # --- table operations (host-side, mirroring InMemoryTable's API) ------

    def insert_batch(self, batch) -> None:
        rows = self._batch_rows(batch)
        self.store.add(rows)
        self._absent_probe_keys.clear()
        self._store_rev += 1
        self._cache_put_rows(rows)

    def insert_rows(self, rows, timestamp: int = 0) -> None:
        dicts = [dict(zip(self._attr_names, r)) for r in rows]
        self.store.add(dicts)
        self._absent_probe_keys.clear()
        self._store_rev += 1
        self._cache_put_rows(dicts)

    def compile_condition(self, expr):
        return self.store.compile_condition(expr, self.definition.id)

    def find_rows(self, expr) -> list[dict]:
        """Authoritative find against the store; read-through refreshes the
        cache (reference: AbstractQueryableRecordTable.find)."""
        rows = list(self.store.find(self.compile_condition(expr)))
        if self.cache_policy is not None:
            for r in rows:
                k = self._key(r)
                if k in self.cache_policy.rows:
                    self.cache_policy.touch(k)
                else:
                    self.cache_policy.put(k, r)
            self._rebuild_cache()
        return rows

    def delete_where(self, expr) -> int:
        compiled = self.compile_condition(expr)
        n = self.store.delete(compiled)
        if self.cache_policy is not None:
            if callable(compiled):
                self.cache_policy.remove_if(compiled)
            else:  # pushdown handle: conservative full invalidation
                self.cache_policy.clear()
            self._rebuild_cache()
        return n

    def update_where(self, expr, updater) -> int:
        compiled = self.compile_condition(expr)
        n = self.store.update(compiled, updater)
        self._absent_probe_keys.clear()
        self._store_rev += 1
        if self.cache_policy is not None:
            if callable(compiled):
                for k, r in list(self.cache_policy.rows.items()):
                    if compiled(r):
                        self.cache_policy.rows[k] = updater(dict(r))
            else:
                # pushdown handle we can't evaluate host-side: drop the
                # whole cache rather than serve stale rows
                self.cache_policy.clear()
            self._rebuild_cache()
        return n

    def update_or_add_where(self, expr, updater, rows) -> int:
        compiled = self.compile_condition(expr)
        n = self.store.update_or_add(compiled, updater, rows)
        self._absent_probe_keys.clear()
        self._store_rev += 1
        if self.cache_policy is not None:
            if n and callable(compiled):
                for k, r in list(self.cache_policy.rows.items()):
                    if compiled(r):
                        self.cache_policy.rows[k] = updater(dict(r))
            elif n:
                # non-callable pushdown handle: conservative invalidation
                self.cache_policy.clear()
            if n == 0:
                for r in rows:
                    self.cache_policy.put(self._key(r), r)
            self._rebuild_cache()
        return n

    def all_rows(self) -> list[tuple]:
        # an empty condition must go through the SPI compile so pushdown
        # adapters receive a handle they understand, not a Python lambda
        match_all = self.compile_condition(None)
        return [tuple(r.get(n) for n in self._attr_names)
                for r in self.store.find(match_all)]

    def shutdown(self) -> None:
        self.store.disconnect()

    def __len__(self) -> int:
        return len(self.all_rows())


# ----------------------------------------------------- host row expressions


def compile_row_expr(expr: Expression, table_id: str, table_attrs: set,
                     prefer: str = "stream") -> Callable:
    """Compile an AST expression to fn(table_row, stream_row) over host row
    dicts — the record-table analogue of the device expression compiler,
    used for CRUD conditions and SET clauses where one side is a store row.
    Unqualified attributes resolve to `prefer` first ('stream' for query
    outputs, 'table' for on-demand queries)."""
    from ..query_api.expression import MathOp

    math_ops = {
        MathOp.ADD: lambda a, b: a + b,
        MathOp.SUBTRACT: lambda a, b: a - b,
        MathOp.MULTIPLY: lambda a, b: a * b,
        MathOp.DIVIDE: lambda a, b: a / b,
        MathOp.MOD: lambda a, b: a % b,
    }
    cmp_ops = PredicateVisitor._OPS

    def compile_(e: Expression) -> Callable:
        if isinstance(e, Constant):
            return lambda t, s, v=e.value: v
        if isinstance(e, Variable):
            name = e.attribute
            if e.stream_id == table_id:
                return lambda t, s, n=name: (t or {}).get(n)
            if e.stream_id is not None:
                return lambda t, s, n=name: (s or {}).get(n)
            if prefer == "table" and name in table_attrs:
                return lambda t, s, n=name: (t or {}).get(n)

            def unqual(t, s, n=name):
                if s is not None and n in s:
                    return s[n]
                return (t or {}).get(n)

            return unqual
        if isinstance(e, Compare):
            l, r, fn = compile_(e.left), compile_(e.right), cmp_ops[e.op]
            return lambda t, s: fn(l(t, s), r(t, s))
        if isinstance(e, And):
            l, r = compile_(e.left), compile_(e.right)
            return lambda t, s: l(t, s) and r(t, s)
        if isinstance(e, Or):
            l, r = compile_(e.left), compile_(e.right)
            return lambda t, s: l(t, s) or r(t, s)
        if isinstance(e, Not):
            inner = compile_(e.expression)
            return lambda t, s: not inner(t, s)
        if isinstance(e, IsNull):
            inner = compile_(e.expression)
            return lambda t, s: inner(t, s) is None
        if isinstance(e, MathExpression):
            l, r, fn = compile_(e.left), compile_(e.right), math_ops[e.op]
            return lambda t, s: fn(l(t, s), r(t, s))
        raise SiddhiAppCreationError(
            f"record-table host expressions do not support "
            f"{type(e).__name__}")

    return compile_(expr)


class RecordTableOutputExecutor:
    """Host executor for query outputs targeting a record table
    (reference: Delete/Update/UpdateOrInsertTableCallback over an
    AbstractRecordTable): decodes the emitted batch and applies per-row
    store operations through the SPI."""

    def __init__(self, table: RecordTableRuntime, output_stream,
                 out_types: dict, out_codec, registry,
                 out_frame_aliases=()) -> None:
        from ..query_api.execution import OutputAction

        self.table = table
        self.action = output_stream.action
        self.out_codec = out_codec
        self.out_names = list(out_types)
        tattrs = set(table.attr_types)
        cond = output_stream.on_condition
        if cond is None:
            raise SiddhiAppCreationError(
                f"{self.action.name} into table requires an ON condition")
        self.cond = compile_row_expr(cond, table.definition.id, tattrs,
                                     prefer="stream")
        self.sets: list[tuple[str, Callable]] = []
        if output_stream.set_attributes:
            for sa in output_stream.set_attributes:
                self.sets.append((
                    sa.table_variable.attribute,
                    compile_row_expr(sa.expression, table.definition.id,
                                     tattrs, prefer="stream")))
        else:
            self.sets = [(n, (lambda t, s, n=n: s.get(n)))
                         for n in table.attr_types if n in out_types]

    def apply(self, out_batch) -> None:
        events = out_batch.to_host_events(self.out_codec)
        self.apply_rows([dict(zip(self.out_names, e.data)) for e in events])

    def apply_rows(self, srows: list[dict]) -> None:
        from ..query_api.execution import OutputAction

        for srow in srows:
            cond = self.cond

            def pred(trow, srow=srow, cond=cond):
                return bool(cond(trow, srow))

            if self.action == OutputAction.DELETE:
                self.table.store.delete(pred)
                if self.table.cache_policy is not None:
                    self.table.cache_policy.remove_if(pred)
            else:
                def updater(trow, srow=srow):
                    for name, fn in self.sets:
                        trow[name] = fn(trow, srow)
                    return trow

                if self.action == OutputAction.UPDATE:
                    n = self.table.store.update(pred, updater)
                else:  # UPDATE_OR_INSERT
                    new_row = {n: srow.get(n) for n in self.table.attr_types}
                    n = self.table.store.update_or_add(pred, updater,
                                                       [new_row])
                    if n == 0 and self.table.cache_policy is not None:
                        self.table.cache_policy.put(
                            self.table._key(new_row), new_row)
                if self.table.cache_policy is not None and n:
                    for k, r in list(self.table.cache_policy.rows.items()):
                        if pred(r):
                            self.table.cache_policy.rows[k] = updater(dict(r))
        if self.table.cache_policy is not None:
            self.table._rebuild_cache()


class RecordCrudRuntime:
    """Host runtime for write-form on-demand queries against a record table
    (reference: the non-find OnDemandQueryRuntimes over record tables).
    Mirrors core/ondemand.py OnDemandCrudRuntime: delete/update/
    update-or-insert reuse the output executor with one synthetic stream
    row; select-insert runs the device select over the source store and
    adds the projected rows."""

    def __init__(self, odq, target: RecordTableRuntime, ctx, registry,
                 source_store=None) -> None:
        from ..query_api.execution import OutputAction, OutputStream
        from ..query_api.expression import Constant

        self.odq = odq
        self.target = target
        self.select_runtime = None
        self.executor = None
        self._srow: dict = {}

        self._const_row = None
        if odq.action == OutputAction.INSERT:
            if odq.input_store_id is None:
                # standalone `select <const exprs> insert into T` — same
                # helper as the in-memory path, so backend choice cannot
                # change query semantics
                from ..core.ondemand import eval_standalone_insert_row
                self._const_row = eval_standalone_insert_row(
                    odq.selector, registry, target.definition)
                return
            import dataclasses as dc

            from ..core.ondemand import OnDemandQueryRuntime
            sel_odq = dc.replace(odq, action=OutputAction.RETURN,
                                 target_id=None)
            self.select_runtime = OnDemandQueryRuntime(
                sel_odq, source_store, ctx, registry)
            return

        out_types: dict = {}
        if odq.action == OutputAction.UPDATE_OR_INSERT:
            # the SELECT list supplies the row to insert on no-match
            tattrs = set(target.attr_types)
            for oa in odq.selector.attributes:
                name = oa.rename or getattr(oa.expression, "attribute", None)
                if name is None:
                    raise SiddhiAppCreationError(
                        "update-or-insert select items need `as` names")
                fn = compile_row_expr(oa.expression, target.definition.id,
                                      tattrs, prefer="table")
                self._srow[name] = fn(None, None)
                out_types[name] = target.attr_types.get(name)

        out_stream = OutputStream(
            action=odq.action, target_id=target.definition.id,
            on_condition=odq.on_condition or Constant(True, "bool"),
            set_attributes=odq.set_attributes)
        self.executor = RecordTableOutputExecutor(
            target, out_stream, out_types, None, registry)

    def execute(self, now: int = 0):
        if self._const_row is not None:
            self.target.store.add([dict(self._const_row)])
            self.target._cache_put_rows(
                [{n: self._const_row.get(n)
                  for n in self.target.attr_types}])
            return []
        if self.select_runtime is not None:
            events = self.select_runtime.execute(now)
            names = [a.name
                     for a in self.select_runtime.output_definition.attributes]
            rows = [dict(zip(names, e.data)) for e in events]
            self.target.store.add(rows)
            self.target._cache_put_rows(
                [{n: r.get(n) for n in self.target.attr_types} for r in rows])
            return []
        self.executor.apply_rows([self._srow])
        return []


def register_all() -> None:
    from ..extension.registry import GLOBAL, ExtensionKind
    GLOBAL.register(ExtensionKind.STORE, "", "inMemory", InMemoryRecordStore)


register_all()
