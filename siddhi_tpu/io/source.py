"""Sources & source mappers — external payloads into stream junctions.

Reference: core/stream/input/source/Source.java:50 (abstract
init/connect/disconnect/pause/resume:113-153, connectWithRetry with exponential
BackoffRetryCounter:155-177), SourceMapper.java:49 (payload → events with
@attributes mappings), InMemorySource.java:63 (@Extension name="inMemory"),
SourceHandler (interception SPI).

TPU note: sources are host-side by definition; their job here is to land
payloads in the junction's staging buffers, where the micro-batcher takes over.
"""

from __future__ import annotations

import json as _json
import time
from typing import Callable, Optional

from ..errors import SiddhiAppCreationError
from ..extension.registry import GLOBAL, ExtensionKind
from .broker import InMemoryBroker, Subscriber


class BackoffRetryCounter:
    """Reference: core/util/transport/BackoffRetryCounter.java — 5ms→1hr
    exponential schedule (the reference's literal table)."""

    _INTERVALS_MS = [5, 50, 500, 5_000, 10_000, 30_000, 60_000, 300_000,
                     1_800_000, 3_600_000]

    def __init__(self) -> None:
        self._i = 0

    def get_time_interval_ms(self) -> int:
        return self._INTERVALS_MS[self._i]

    def increment(self) -> None:
        if self._i < len(self._INTERVALS_MS) - 1:
            self._i += 1

    def reset(self) -> None:
        self._i = 0


class ConnectionUnavailableException(Exception):
    """Reference: core/exception/ConnectionUnavailableException.java."""


class SourceMapper:
    """Payload → rows SPI (reference: SourceMapper.java:49). Subclasses parse
    one transport message into row tuples ordered per the stream schema."""

    def init(self, stream_definition, options: dict, attribute_mappings) -> None:
        self.definition = stream_definition
        self.options = options
        self.attribute_mappings = attribute_mappings  # list[(attr, path)] or None

    def map(self, payload) -> list[tuple]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    """@map(type='passThrough') — payload already is a row (tuple/list) or a
    list of rows (reference: PassThroughSourceMapper.java)."""

    def map(self, payload) -> list[tuple]:
        if isinstance(payload, (list,)) and payload and isinstance(payload[0], (list, tuple)):
            return [tuple(r) for r in payload]
        if isinstance(payload, (list, tuple)):
            return [tuple(payload)]
        raise SiddhiAppCreationError(
            f"passThrough mapper expects row tuples, got {type(payload).__name__}")


class JsonSourceMapper(SourceMapper):
    """@map(type='json') — parses {"event": {attr: value}} | [events] | a bare
    attr dict, with optional @attributes(attr='json.path') dotted-path
    mappings (the core behavior of the siddhi-map-json extension)."""

    def map(self, payload) -> list[tuple]:
        data = _json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        events = data if isinstance(data, list) else [data]
        rows = []
        for ev in events:
            if isinstance(ev, dict) and "event" in ev:
                ev = ev["event"]
            rows.append(self._row(ev))
        return rows

    def _row(self, ev: dict) -> tuple:
        if self.attribute_mappings:
            return tuple(self._path(ev, path)
                         for _attr, path in self.attribute_mappings)
        return tuple(ev[a.name] for a in self.definition.attributes)

    @staticmethod
    def _path(obj, path: str):
        cur = obj
        for part in path.replace("$.", "").split("."):
            cur = cur[part]
        return cur


class FrameSourceMapper(SourceMapper):
    """@map(type='frame') — SXF1 binary columnar frames (io/wire.py) over
    any transport. Decodes the dictionary-encoded columns and materializes
    row tuples in schema order (the Source SPI hands rows to the junction;
    the REST frames endpoint keeps the columns intact all the way to the
    ingress ring — use that path when throughput matters)."""

    def map(self, payload) -> list[tuple]:
        from . import wire
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise SiddhiAppCreationError(
                f"frame mapper expects bytes, got {type(payload).__name__}")
        plan = wire.schema_plan(self.definition)
        rows: list[tuple] = []
        for frame in wire.iter_frames(payload):
            _ts, cols, n = wire.decode_frame(frame, plan)
            if n == 0:
                continue
            lists = []
            for name, _dt, code in plan:
                col = cols[name]
                if code == "s":
                    lists.append(wire.materialize_strings(col).tolist())
                else:
                    lists.append(col.tolist())
            rows.extend(zip(*lists))
        return rows


class Source:
    """Transport SPI (reference: Source.java:50). Lifecycle:
    init → connect_with_retry → [pause/resume] → disconnect."""

    def init(self, stream_definition, options: dict, mapper: SourceMapper,
             handler: Callable[[list[tuple]], None], ctx) -> None:
        self.definition = stream_definition
        self.options = options
        self.mapper = mapper
        self._handler = handler
        self.ctx = ctx
        self._paused = False
        self._pending: list = []
        #: paused-payload buffer bound: a paused source must not become the
        #: unbounded buffer the junction bound just removed — past this the
        #: OLDEST pending payload is shed and counted (pause.buffer.size=)
        self._pending_cap = int(options.get("pause.buffer.size") or 65536)
        #: persistent reconnect backoff (reference: Source.connectWithRetry
        #: :155-177 keeps ONE counter per source) — repeated flaps escalate
        #: the interval across connect_with_retry calls until a connect
        #: succeeds, which resets it to the 5 ms floor
        self._retry_counter = BackoffRetryCounter()

    # -- transport hooks -----------------------------------------------------

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        raise NotImplementedError

    def pause(self) -> None:
        """Backpressure hook: stop delivering to the junction; payloads
        arriving while paused buffer (bounded) in `_pending` until resume."""
        self._paused = True

    def resume(self) -> None:
        if not self._paused:
            return
        self._paused = False
        pending, self._pending = self._pending, []
        for payload in pending:
            # internal re-drain: NOT via on_payload — instance-level
            # wrappers (fault injection, flap schedules) must only see NEW
            # transport callbacks, never this replay. _deliver_payload
            # re-checks _paused, so a re-pause mid-drain re-buffers the rest
            self._deliver_payload(payload)

    @property
    def paused(self) -> bool:
        return self._paused

    # -- runtime -------------------------------------------------------------

    def on_payload(self, payload) -> None:
        """Transport callback: map + hand rows to the junction."""
        self._deliver_payload(payload)

    def _deliver_payload(self, payload) -> None:
        if self._paused:
            if len(self._pending) >= self._pending_cap:
                self._pending.pop(0)  # shed oldest, keep the fresh payload
                stats = getattr(getattr(self, "ctx", None), "statistics",
                                None)
                if stats is not None:
                    stats.track_ingress_drop(self.definition.id,
                                             "source.pending", 1)
            self._pending.append(payload)
            return
        self._handler(self.mapper.map(payload))

    def connect_with_retry(self, max_attempts: int = 3,
                           sleep: Callable[[float], None] = time.sleep) -> None:
        """Reference: Source.connectWithRetry:155-177 — exponential backoff on
        ConnectionUnavailableException. max_attempts bounds the synchronous
        build (the reference retries forever on a scheduler thread). The
        backoff counter is the SOURCE'S persistent one (mirror of the
        sink-side reconnect): a transport that flaps across repeated calls
        keeps escalating; only a successful connect resets it."""
        counter = getattr(self, "_retry_counter", None)
        if counter is None:  # source used without init() (tests)
            counter = self._retry_counter = BackoffRetryCounter()
        attempt = 0
        while True:
            try:
                self.connect()
                counter.reset()
                return
            except ConnectionUnavailableException:
                attempt += 1
                stats = getattr(getattr(self, "ctx", None), "statistics", None)
                if stats is not None:  # operators see flapping transports
                    stats.track_source_retry(self.definition.id)
                if attempt >= max_attempts:
                    raise
                sleep(counter.get_time_interval_ms() / 1000.0)
                counter.increment()


class InMemorySource(Source):
    """@source(type='inMemory', topic='x') (reference: InMemorySource.java:63)."""

    def connect(self) -> None:
        topic = self.options.get("topic")
        if not topic:
            raise SiddhiAppCreationError("inMemory source needs topic=")
        self._sub = InMemoryBroker.subscribe_fn(topic, self.on_payload)

    def disconnect(self) -> None:
        if getattr(self, "_sub", None) is not None:
            InMemoryBroker.unsubscribe(self._sub)
            self._sub = None


class TimerSource(Source):
    """@source(type='timer', interval='1000') — poll-driven synthetic source
    for tests/benchmarks; fires one empty-keyed row per poll tick."""

    def connect(self) -> None:
        self._connected = True

    def disconnect(self) -> None:
        self._connected = False


def register_all() -> None:
    GLOBAL.register(ExtensionKind.SOURCE, "", "inMemory", InMemorySource)
    GLOBAL.register(ExtensionKind.SOURCE, "", "timer", TimerSource)
    GLOBAL.register(ExtensionKind.SOURCE_MAPPER, "", "passThrough",
                    PassThroughSourceMapper)
    GLOBAL.register(ExtensionKind.SOURCE_MAPPER, "", "json", JsonSourceMapper)
    GLOBAL.register(ExtensionKind.SOURCE_MAPPER, "", "frame",
                    FrameSourceMapper)


register_all()
