"""IO layer: sources, sinks, mappers, in-memory broker, distributed transports
(reference: core/stream/input/source/, core/stream/output/sink/,
core/util/transport/)."""

from .broker import InMemoryBroker, Subscriber
from .record_table import (InMemoryRecordStore, RecordStore,
                           RecordTableRuntime,
                           StoreConditionVisitor)
from .sink import (
    BroadcastStrategy,
    DistributedSink,
    DistributionStrategy,
    InMemorySink,
    JsonSinkMapper,
    LogSink,
    PartitionedStrategy,
    PassThroughSinkMapper,
    RoundRobinStrategy,
    Sink,
    SinkMapper,
    TextSinkMapper,
)
from .source import (
    BackoffRetryCounter,
    ConnectionUnavailableException,
    InMemorySource,
    JsonSourceMapper,
    PassThroughSourceMapper,
    Source,
    SourceMapper,
)

__all__ = [
    "BackoffRetryCounter",
    "BroadcastStrategy",
    "ConnectionUnavailableException",
    "DistributedSink",
    "DistributionStrategy",
    "InMemoryBroker",
    "InMemorySink",
    "InMemoryRecordStore",
    "InMemorySource",
    "JsonSinkMapper",
    "JsonSourceMapper",
    "LogSink",
    "PartitionedStrategy",
    "PassThroughSinkMapper",
    "PassThroughSourceMapper",
    "RecordStore",
    "RecordTableRuntime",
    "RoundRobinStrategy",
    "StoreConditionVisitor",
    "Sink",
    "SinkMapper",
    "Source",
    "SourceMapper",
    "Subscriber",
    "TextSinkMapper",
]
