"""Extension registry — `namespace:name` SPI resolution.

Reference: core/util/SiddhiExtensionLoader.java:33 discovers @Extension classes
via ClassIndex/OSGi into 13 typed namespaces. The TPU build uses an explicit
Python registry with typed kinds; extensions register with decorators and are
resolved at query-plan time. No classpath scanning — registration is explicit
(import-time) or via `SiddhiManager.set_extension`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional


class ExtensionKind(enum.Enum):
    FUNCTION = "function"  # scalar fn: executor/function/FunctionExecutor.java
    AGGREGATOR = "aggregator"  # selector/attribute/aggregator/*
    WINDOW = "window"  # processor/stream/window/*
    STREAM_PROCESSOR = "stream_processor"  # processor/stream/*
    STREAM_FUNCTION = "stream_function"
    SOURCE = "source"
    SINK = "sink"
    SOURCE_MAPPER = "source_mapper"
    SINK_MAPPER = "sink_mapper"
    TABLE = "table"
    STORE = "store"
    SCRIPT = "script"
    INCREMENTAL_AGGREGATOR = "incremental_aggregator"
    DISTRIBUTION_STRATEGY = "distribution_strategy"


@dataclass(frozen=True)
class Parameter:
    """Declared extension parameter (reference:
    siddhi-annotations @Parameter — name/type/optional/defaultValue/
    description, validated by
    core/util/extension/validator/InputParameterValidator.java)."""

    name: str
    #: accepted type names: int, long, float, double, bool, string, time
    #: (int ms from `<n> sec` literals), attribute (a stream attr reference)
    types: tuple
    optional: bool = False
    default: object = None
    doc: str = ""


@dataclass(frozen=True)
class ExtensionMeta:
    """@Extension-style metadata: drives parse-time parameter validation
    and the doc-gen parameter tables."""

    description: str = ""
    parameters: tuple = ()
    #: last declared parameter may repeat (varargs-style)
    repeat_last: bool = False


def _param_type_of(value) -> str:
    if isinstance(value, bool):
        return "bool"
    if isinstance(value, int):
        return "int"
    if isinstance(value, float):
        return "double"
    if isinstance(value, str):
        return "string"
    from ..query_api.expression import Variable
    if isinstance(value, Variable):
        return "attribute"
    return type(value).__name__


#: type-name compatibility: a literal of row type satisfies a declared col
_TYPE_OK = {
    ("int", "int"), ("int", "long"), ("int", "time"), ("int", "double"),
    ("int", "float"),
    ("double", "double"), ("double", "float"),
    ("bool", "bool"),
    ("string", "string"),
    ("attribute", "attribute"),
}


@dataclass
class Registry:
    _entries: dict[tuple[ExtensionKind, str], object] = field(default_factory=dict)
    _meta: dict[tuple[ExtensionKind, str], "ExtensionMeta"] = field(
        default_factory=dict)

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace.lower()}:{name.lower()}" if namespace else name.lower()

    def register(self, kind: ExtensionKind, namespace: str, name: str, impl: object,
                 overwrite: bool = True, meta: Optional[ExtensionMeta] = None) -> None:
        k = (kind, self._key(namespace, name))
        if not overwrite and k in self._entries:
            raise ValueError(f"extension {k} already registered")
        self._entries[k] = impl
        if meta is not None:
            self._meta[k] = meta

    def meta_of(self, kind: ExtensionKind, namespace: str,
                name: str) -> Optional[ExtensionMeta]:
        return self._meta.get((kind, self._key(namespace, name)))

    def validate_params(self, kind: ExtensionKind, namespace: str, name: str,
                        params, what: str = "extension") -> None:
        """Parse-time arity/type check against declared Parameter metadata
        (reference: InputParameterValidator.validateExpressionExecutors).
        Raises SiddhiAppCreationError NAMING the offending parameter; no-op
        for extensions without metadata."""
        meta = self.meta_of(kind, namespace, name)
        if meta is None:
            return
        from ..errors import SiddhiAppCreationError
        full = f"{namespace}:{name}" if namespace else name
        decl = list(meta.parameters)
        n_required = sum(1 for p in decl if not p.optional)
        if len(params) < n_required:
            missing = decl[len(params)]
            raise SiddhiAppCreationError(
                f"{what} {full!r} needs parameter "
                f"{len(params) + 1} ({missing.name}: "
                f"{'|'.join(missing.types)}) — "
                f"{missing.doc or 'required'}")
        if len(params) > len(decl) and not meta.repeat_last:
            raise SiddhiAppCreationError(
                f"{what} {full!r} takes at most {len(decl)} parameter(s) "
                f"({', '.join(p.name for p in decl)}), got {len(params)}")
        for i, v in enumerate(params):
            p = decl[min(i, len(decl) - 1)]
            got = _param_type_of(v)
            if not any((got, t) in _TYPE_OK for t in p.types):
                raise SiddhiAppCreationError(
                    f"{what} {full!r} parameter {i + 1} ({p.name}) must be "
                    f"{'|'.join(p.types)}, got {got} ({v!r})")

    def lookup(self, kind: ExtensionKind, namespace: str, name: str) -> Optional[object]:
        return self._entries.get((kind, self._key(namespace, name)))

    def require(self, kind: ExtensionKind, namespace: str, name: str) -> object:
        impl = self.lookup(kind, namespace, name)
        if impl is None:
            full = f"{namespace}:{name}" if namespace else name
            raise KeyError(f"no {kind.value} extension named {full!r}")
        return impl

    def names(self, kind: ExtensionKind) -> list[str]:
        return sorted(k[1] for k in self._entries if k[0] == kind)

    def copy(self) -> "Registry":
        r = Registry()
        r._entries = dict(self._entries)
        r._meta = dict(self._meta)
        return r


#: process-global default registry; SiddhiManager snapshots it per manager so
#: per-manager set_extension doesn't leak globally.
GLOBAL = Registry()


def register_global(kind: ExtensionKind, name: str, namespace: str = "",
                    meta: Optional[ExtensionMeta] = None):
    """Decorator: @register_global(ExtensionKind.WINDOW, 'length')."""

    def deco(obj):
        GLOBAL.register(kind, namespace, name, obj, meta=meta)
        return obj

    return deco
