"""Extension registry — `namespace:name` SPI resolution.

Reference: core/util/SiddhiExtensionLoader.java:33 discovers @Extension classes
via ClassIndex/OSGi into 13 typed namespaces. The TPU build uses an explicit
Python registry with typed kinds; extensions register with decorators and are
resolved at query-plan time. No classpath scanning — registration is explicit
(import-time) or via `SiddhiManager.set_extension`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional


class ExtensionKind(enum.Enum):
    FUNCTION = "function"  # scalar fn: executor/function/FunctionExecutor.java
    AGGREGATOR = "aggregator"  # selector/attribute/aggregator/*
    WINDOW = "window"  # processor/stream/window/*
    STREAM_PROCESSOR = "stream_processor"  # processor/stream/*
    STREAM_FUNCTION = "stream_function"
    SOURCE = "source"
    SINK = "sink"
    SOURCE_MAPPER = "source_mapper"
    SINK_MAPPER = "sink_mapper"
    TABLE = "table"
    STORE = "store"
    SCRIPT = "script"
    INCREMENTAL_AGGREGATOR = "incremental_aggregator"
    DISTRIBUTION_STRATEGY = "distribution_strategy"


@dataclass
class Registry:
    _entries: dict[tuple[ExtensionKind, str], object] = field(default_factory=dict)

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace.lower()}:{name.lower()}" if namespace else name.lower()

    def register(self, kind: ExtensionKind, namespace: str, name: str, impl: object,
                 overwrite: bool = True) -> None:
        k = (kind, self._key(namespace, name))
        if not overwrite and k in self._entries:
            raise ValueError(f"extension {k} already registered")
        self._entries[k] = impl

    def lookup(self, kind: ExtensionKind, namespace: str, name: str) -> Optional[object]:
        return self._entries.get((kind, self._key(namespace, name)))

    def require(self, kind: ExtensionKind, namespace: str, name: str) -> object:
        impl = self.lookup(kind, namespace, name)
        if impl is None:
            full = f"{namespace}:{name}" if namespace else name
            raise KeyError(f"no {kind.value} extension named {full!r}")
        return impl

    def names(self, kind: ExtensionKind) -> list[str]:
        return sorted(k[1] for k in self._entries if k[0] == kind)

    def copy(self) -> "Registry":
        r = Registry()
        r._entries = dict(self._entries)
        return r


#: process-global default registry; SiddhiManager snapshots it per manager so
#: per-manager set_extension doesn't leak globally.
GLOBAL = Registry()


def register_global(kind: ExtensionKind, name: str, namespace: str = ""):
    """Decorator: @register_global(ExtensionKind.WINDOW, 'length')."""

    def deco(obj):
        GLOBAL.register(kind, namespace, name, obj)
        return obj

    return deco
