"""`python -m siddhi_tpu.doctor` — turn diagnostic evidence into a diagnosis.

    python -m siddhi_tpu.doctor <bundle-dir>              # offline bundle
    python -m siddhi_tpu.doctor <bundle> --baseline <b0>  # + regression diff
    python -m siddhi_tpu.doctor --live http://host:9090 --app MyApp
    python -m siddhi_tpu.doctor <bundle> --json           # machine readable

Loads a flight-recorder bundle (telemetry/recorder.py) — or, with --live,
scrapes a running service's statistics endpoint into an in-memory pseudo
bundle — and walks the evidence the way an on-call engineer would:

  1. per breached SLO objective, rank the pipeline stages (stage | h2d |
     device | sink) by recorded latency and name the DOMINANT one, using
     the per-stream stage percentiles first and the slow-batch exemplars'
     stage shares as the tie-breaker/fallback;
  2. check the failure surfaces the engine already counts: open circuit
     breakers, dead-lettered/dropped rows, device-capacity overflow,
     recompile storms (many distinct widths per query), a saturated
     ingress ring, stored error entries;
  3. with --baseline, diff per-stage p99s against an earlier bundle and
     flag stages that regressed past --threshold (default 2.0x).

Findings print ranked (critical > warning > info), each with the evidence
line that produced it. Exit codes are CI-stable:

  0  healthy — no warning/critical findings (info-only is healthy)
  1  the bundle is unreadable, has an unknown schema version, or the
     --live scrape failed
  3  degraded — at least one warning/critical finding

(2 is deliberately unused: argparse exits 2 on bad usage.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from .telemetry.recorder import SCHEMA_VERSION

EXIT_OK = 0
EXIT_BAD_BUNDLE = 1
EXIT_DEGRADED = 3

SEVERITIES = ("critical", "warning", "info")

#: stages the dominant-stage ranking considers (e2e is the total, not a
#: stage; "stage" is batch assembly/staging time)
STAGES = ("stage", "h2d", "device", "sink")

#: distinct compiled widths per query past which we call it a storm
COMPILE_STORM_WIDTHS = 8

#: share of admitted rows diverted as kind="late" past which the diversion
#: stops being stragglers and becomes a burst (disorder > allowed.lateness)
LATE_BURST_SHARE = 0.01

#: share of the configured state budget past which attach headroom is an
#: on-call concern (stats["cost"]; the SL501 admission gate refuses at 100%)
BUDGET_NEAR_EXHAUSTION = 0.8

#: live/predicted state drift past which the static cost model is lying
#: (same band tools/cost_calibrate.py gates in CI)
COST_DRIFT_BAND = 2.0


class BundleError(Exception):
    pass


# --------------------------------------------------------------------------- #
# loading
# --------------------------------------------------------------------------- #


def load_bundle(path: str) -> dict:
    """Read a recorder bundle directory into one dict keyed by section
    (manifest/stats/traces/logs/plan/config). Raises BundleError on a
    missing manifest or an unknown schema version."""
    manifest_path = os.path.join(path, "manifest.json")
    if not os.path.isfile(manifest_path):
        raise BundleError(f"{path}: not a diagnostic bundle "
                          "(no manifest.json)")
    bundle: dict = {}
    for section in ("manifest", "stats", "traces", "logs", "plan", "config"):
        fpath = os.path.join(path, section + ".json")
        if not os.path.isfile(fpath):
            bundle[section] = None
            continue
        try:
            with open(fpath) as f:
                bundle[section] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise BundleError(f"{fpath}: unreadable ({e})") from e
    ver = (bundle["manifest"] or {}).get("schema_version")
    if ver != SCHEMA_VERSION:
        raise BundleError(
            f"{path}: bundle schema version {ver!r} != supported "
            f"{SCHEMA_VERSION}")
    return bundle


def load_live(url: str, app: str, token: Optional[str] = None) -> dict:
    """Scrape a running service into a pseudo-bundle: the statistics
    report carries everything the stage/SLO analysis needs (traces ride
    in as slow_batches)."""
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"{url.rstrip('/')}/siddhi-apps/{app}/statistics")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            stats = json.load(resp)
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        raise BundleError(f"live scrape of {url!r} failed: {e}") from e
    return {
        "manifest": {"schema_version": SCHEMA_VERSION, "app": app,
                     "trigger": {"kind": "live", "reason": url}},
        "stats": stats,
        "traces": {"recent": [], "slow_batches":
                   stats.get("slow_batches", [])},
        "logs": [], "plan": None, "config": None,
    }


# --------------------------------------------------------------------------- #
# analysis
# --------------------------------------------------------------------------- #


def _finding(severity: str, title: str, evidence: str,
             objective: Optional[str] = None) -> dict:
    return {"severity": severity, "title": title, "evidence": evidence,
            "objective": objective}


def _stage_p99s(stats: dict, stream: Optional[str] = None) -> dict:
    """{stage: p99_ms} merged across streams (or for one stream)."""
    out: dict = {}
    streams = (stats.get("latency") or {}).get("streams") or {}
    for sid, stages in streams.items():
        if stream is not None and sid != stream:
            continue
        for stage, summary in stages.items():
            if stage not in STAGES:
                continue
            p99 = summary.get("p99_ms")
            if p99 is not None and p99 > out.get(stage, -1.0):
                out[stage] = p99
    return out


def _stage_shares_from_exemplars(traces: dict,
                                 query: Optional[str] = None) -> dict:
    """{stage: mean_ms} over the slow-batch exemplars (optionally only the
    ones a given query participated in) — the fallback ranking when the
    histogram percentiles don't isolate the scope."""
    out: dict = {s: 0.0 for s in STAGES}
    n = 0
    for s in (traces or {}).get("slow_batches") or []:
        if query is not None and query not in (s.get("queries") or ()):
            continue
        stages = s.get("stages_ms") or {}
        for stage in STAGES:
            out[stage] += float(stages.get(stage, 0.0))
        n += 1
    if n == 0:
        return {}
    return {stage: total / n for stage, total in out.items()}


def dominant_stage(stats: dict, traces: dict, scope: str) -> Optional[tuple]:
    """(stage, ms, basis) for one objective scope ("stream:X" /
    "query:Q"), or None when there is no stage evidence at all."""
    scope_type, _, name = scope.partition(":")
    ranking: dict = {}
    basis = ""
    if scope_type == "stream":
        ranking = _stage_p99s(stats, name)
        basis = f"stage p99 on stream {name!r}"
    elif scope_type == "query":
        ranking = _stage_shares_from_exemplars(traces, name)
        basis = f"mean stage share of slow batches through query {name!r}"
    if not ranking:
        ranking = _stage_p99s(stats)
        basis = "stage p99 across all streams"
    if not ranking:
        ranking = _stage_shares_from_exemplars(traces)
        basis = "mean stage share of slow-batch exemplars"
    if not ranking:
        return None
    stage = max(ranking, key=lambda s: ranking[s])
    return stage, ranking[stage], basis


def analyze(bundle: dict, baseline: Optional[dict] = None,
            threshold: float = 2.0) -> list[dict]:
    """All findings, ranked most-severe first."""
    stats = bundle.get("stats") or {}
    traces = bundle.get("traces") or {}
    findings: list[dict] = []

    # 1. breached objectives → dominant stage
    slo = stats.get("slo") or {}
    for oid, rep in (slo.get("objectives") or {}).items():
        if rep.get("state") != "breached":
            if rep.get("breaches", 0) > 0:
                findings.append(_finding(
                    "info", f"objective {oid} breached earlier but "
                    "recovered",
                    f"{rep['breaches']} breach(es), "
                    f"{rep.get('recoveries', 0)} recovery(ies)", oid))
            continue
        dom = dominant_stage(stats, traces, rep.get("scope", ""))
        burn = (rep.get("fast") or {}).get("burn_rate", 0.0)
        if dom is None:
            findings.append(_finding(
                "critical", f"objective {oid} is breached",
                f"fast-window burn rate {burn:.2f}; no stage evidence "
                "recorded", oid))
            continue
        stage, ms, basis = dom
        findings.append(_finding(
            "critical",
            f"objective {oid} is breached — dominant stage: {stage}",
            f"fast-window burn rate {burn:.2f}; {basis} = {ms:.2f} ms",
            oid))

    # 2. front-tier failover surfaces (parallel/front_tier.py bundles):
    # a dead shard owner / unowned slots means frames are spooling or
    # diverting RIGHT NOW — the on-call page for the multi-host tier
    ft = stats.get("front_tier") or {}
    if ft:
        def _slots(slots):
            s = ", ".join(str(x) for x in slots[:12])
            return s + (f", … ({len(slots)} total)"
                        if len(slots) > 12 else "")
        spool = ft.get("spool") or {}
        depth = spool.get("frames", 0)
        dead_hosts = [u for u, h in (ft.get("hosts") or {}).items()
                      if not h.get("up")]
        unowned = ft.get("unowned_slots") or []
        dead_slots = ft.get("dead_owner_slots") or []
        if unowned:
            findings.append(_finding(
                "critical",
                "unowned shard slots: frames divert to the error store",
                f"slots [{_slots(unowned)}] have NO live owner; "
                f"{ft.get('unowned_diverts', 0)} divert(s), spool depth "
                f"{depth} frame(s) — replay via /errors/replay "
                "(kind=unowned) once a host adopts the shards"))
        if dead_slots:
            findings.append(_finding(
                "critical",
                "dead shard owner: slots routed to an unreachable host",
                f"host(s) {', '.join(dead_hosts) or '?'} down; slots "
                f"[{_slots(dead_slots)}] affected, spool depth {depth} "
                "frame(s) awaiting takeover/replay"))
        elif depth:
            findings.append(_finding(
                "warning", "router spool is non-empty",
                f"{depth} frame(s) spooled awaiting replay; failovers so "
                f"far: {ft.get('failovers_total', 0)}"))

    # 3. engine failure surfaces
    for q, br in (stats.get("breakers") or {}).items():
        if br.get("state") and br["state"] != "closed":
            findings.append(_finding(
                "critical", f"circuit breaker for query {q!r} is "
                f"{br['state']}",
                f"{br.get('failures', 0)} failure(s), "
                f"{br.get('diverted_rows', 0)} row(s) diverted"))
    for tid, t in sorted((stats.get("tenants") or {}).items()):
        if t.get("diverting"):
            dom = t.get("dominant_query")
            findings.append(_finding(
                "warning", f"tenant {tid!r} is over its device-time quota",
                f"{t.get('device_ms_window', 0):.1f} ms spent of "
                f"{t.get('device_ms_budget')} ms budget in the last "
                f"{t.get('window_s', 0):.0f} s"
                + (f"; dominant query {dom!r}" if dom else "")
                + f"; {t.get('diverted_rows', 0)} row(s) diverted "
                "(replayable) — siblings unaffected"))
        elif t.get("breaches"):
            findings.append(_finding(
                "info", f"tenant {tid!r} breached its quota earlier",
                f"{t['breaches']} breach(es); now under budget"))
    splices = (stats.get("splices") or {}).get("counts") or {}
    if splices.get("failed"):
        findings.append(_finding(
            "warning", "query splices failed (fell back to standalone "
            "dispatch)",
            f"{splices['failed']} failure(s) — see the flight recorder's "
            "splice_failure bundle(s); affected queries run unfused"))
    dead = stats.get("sink_dead_letters") or {}
    if sum(dead.values()):
        findings.append(_finding(
            "warning", "sink dead-letters present",
            ", ".join(f"{s}: {n}" for s, n in sorted(dead.items()))))
    dropped = stats.get("sink_dropped") or {}
    if sum(dropped.values()):
        findings.append(_finding(
            "warning", "sinks dropped rows (on.error=LOG)",
            ", ".join(f"{s}: {n}" for s, n in sorted(dropped.items()))))
    overflow = stats.get("overflow") or {}
    if overflow:
        findings.append(_finding(
            "critical", "device-capacity overflow: results are missing rows",
            ", ".join(f"{k}: {n}" for k, n in sorted(overflow.items()))))
    for q, widths in (stats.get("compile_widths") or {}).items():
        distinct = len(set(widths))
        if distinct >= COMPILE_STORM_WIDTHS:
            findings.append(_finding(
                "warning", f"recompile storm on query {q!r}",
                f"{distinct} distinct compiled widths "
                f"({len(widths)} compiles) — unstable batch shapes"))
    for sid, snap in (stats.get("ingress_pipeline") or {}).items():
        cap = snap.get("ring_capacity") or 0
        hwm = snap.get("ring_depth_hwm") or 0
        if cap and hwm >= cap:
            findings.append(_finding(
                "warning", f"ingress ring for {sid!r} hit capacity",
                f"depth high-watermark {hwm} of {cap} — producers "
                "outran the feeder (backpressure/shedding engaged)"))
    es = stats.get("error_store") or {}
    if es.get("entries"):
        findings.append(_finding(
            "info", "error store holds replayable entries",
            f"{es['entries']} entry(ies), "
            f"{es.get('dropped_error_entries', 0)} dropped"))
    for sid, wm in sorted((stats.get("watermarks") or {}).items()):
        late, admitted = wm.get("late", 0), wm.get("admitted", 0)
        if late and admitted and late / admitted >= LATE_BURST_SHARE:
            findings.append(_finding(
                "warning", f"late-event burst on stream {sid!r}",
                f"{late} of {admitted} row(s) arrived behind the watermark "
                f"and were diverted (kind=\"late\") — disorder exceeds "
                f"allowed.lateness={wm.get('lateness_ms', 0)} ms; raise the "
                "lateness budget or replay via POST /errors/replay"))
        elif late:
            findings.append(_finding(
                "info", f"late events diverted on stream {sid!r}",
                f"{late} row(s) behind the watermark sit in the error "
                "store (kind=\"late\", replayable)"))
    rec = stats.get("recovery") or {}
    if rec.get("recoveries"):
        findings.append(_finding(
            "info", "app recovered from a crash/restart",
            f"{rec['recoveries']} recovery(ies), "
            f"{rec.get('wal_replayed', 0)} WAL event(s) replayed"))
    upg = stats.get("upgrade") or {}
    if upg.get("rollbacks"):
        findings.append(_finding(
            "warning", "hot-swap upgrade rolled back",
            f"{upg['rollbacks']} rollback(s) — v2 failed pre-commit"))

    # 2b. capacity certification (analysis/cost.py via stats["cost"])
    cost = stats.get("cost") or {}
    budget = cost.get("budget") or {}
    budget_bytes = budget.get("state_bytes")
    if budget_bytes:
        used = max(cost.get("live_state_bytes") or 0,
                   cost.get("predicted_state_bytes") or 0)
        share = used / budget_bytes
        if share > BUDGET_NEAR_EXHAUSTION:
            dom = cost.get("dominant") or {}
            dom_note = (f"; dominant element (SL505): {dom['element']!r} "
                        f"holds {dom['state_bytes']} B "
                        f"({dom.get('share', 0):.0%})" if dom else "")
            findings.append(_finding(
                "warning" if share <= 1.0 else "critical",
                "state budget near exhaustion" if share <= 1.0
                else "state budget exceeded",
                f"{used} of {budget_bytes} B ({share:.0%}) of the "
                f"configured budget ({budget.get('source', '?')})"
                f"{dom_note} — the next attach may be refused (SL501)"))
    ratio = cost.get("state_ratio")
    if ratio is not None and cost.get("live_state_bytes") and not (
            1.0 / COST_DRIFT_BAND <= ratio <= COST_DRIFT_BAND):
        findings.append(_finding(
            "warning", "cost-model drift: live state diverges from the "
            "static prediction",
            f"live {cost.get('live_state_bytes')} B vs predicted "
            f"{cost.get('predicted_state_bytes')} B ({ratio:.2f}x, band "
            f"{COST_DRIFT_BAND:.1f}x) — an operator allocates state the "
            "model does not price; run tools/cost_calibrate.py"))

    # 4. baseline regression diff
    if baseline is not None:
        base_stats = baseline.get("stats") or {}
        now_p99 = _stage_p99s(stats)
        base_p99 = _stage_p99s(base_stats)
        for stage, ms in sorted(now_p99.items()):
            b = base_p99.get(stage)
            if b and b > 0 and ms / b >= threshold:
                findings.append(_finding(
                    "warning",
                    f"stage {stage!r} p99 regressed {ms / b:.1f}x vs "
                    "baseline",
                    f"{b:.2f} ms -> {ms:.2f} ms "
                    f"(threshold {threshold:.1f}x)"))

    findings.sort(key=lambda f: SEVERITIES.index(f["severity"]))
    return findings


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #


def _render(bundle: dict, findings: list[dict]) -> str:
    man = bundle.get("manifest") or {}
    trig = man.get("trigger") or {}
    lines = [
        f"doctor: app {man.get('app', '?')!r}, trigger "
        f"{trig.get('kind', '?')}"
        + (f" ({trig['reason']})" if trig.get("reason") else ""),
    ]
    if not findings:
        lines.append("  healthy: no findings")
        return "\n".join(lines)
    icons = {"critical": "!!", "warning": " !", "info": "  "}
    for i, f in enumerate(findings, 1):
        lines.append(f"{icons[f['severity']]} {i}. "
                     f"[{f['severity'].upper()}] {f['title']}")
        lines.append(f"       {f['evidence']}")
    worst = findings[0]["severity"]
    lines.append(f"diagnosis: {sum(1 for f in findings)} finding(s), "
                 f"worst severity {worst}")
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.doctor",
        description="Analyze a flight-recorder diagnostic bundle (or a "
                    "live service) and print a ranked diagnosis.")
    p.add_argument("bundle", nargs="?",
                   help="path to a diagnostic bundle directory")
    p.add_argument("--baseline", metavar="BUNDLE",
                   help="earlier bundle to diff stage p99s against")
    p.add_argument("--live", metavar="URL",
                   help="scrape a running service instead of a bundle")
    p.add_argument("--app", help="app name (required with --live)")
    p.add_argument("--token", help="bearer token for --live")
    p.add_argument("--threshold", type=float, default=2.0,
                   help="baseline regression ratio that flags a stage "
                        "(default 2.0)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(argv)

    try:
        if args.live:
            if not args.app:
                p.error("--live requires --app")
            bundle = load_live(args.live, args.app, args.token)
        elif args.bundle:
            bundle = load_bundle(args.bundle)
        else:
            p.error("need a bundle path or --live URL")
        baseline = load_bundle(args.baseline) if args.baseline else None
    except BundleError as e:
        print(f"doctor: {e}", file=sys.stderr)
        return EXIT_BAD_BUNDLE

    findings = analyze(bundle, baseline, threshold=args.threshold)
    degraded = any(f["severity"] in ("critical", "warning")
                   for f in findings)
    if args.as_json:
        print(json.dumps({
            "app": (bundle.get("manifest") or {}).get("app"),
            "schema_version": SCHEMA_VERSION,
            "findings": findings,
            "degraded": degraded,
        }, indent=1))
    else:
        print(_render(bundle, findings))
    return EXIT_DEGRADED if degraded else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
