"""Definitions: typed schemas for streams, tables, windows, triggers, aggregations.

TPU-native re-design of the reference AST definition layer
(reference: modules/siddhi-query-api/src/main/java/io/siddhi/query/api/definition/).
Unlike the reference's mutable builder classes, these are frozen dataclasses: a
definition is a static schema that the compiler lowers to fixed dtypes/shapes, which
is what XLA needs (static shapes, no per-event polymorphism).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from .annotation import Annotation


class AttributeType(enum.Enum):
    """Typed attributes (reference: query/api/definition/Attribute.java Type enum).

    Device mapping (see core/dtypes.py): STRING is dictionary-encoded to int32 codes
    at ingestion so string equality/group-by runs on device as integer ops; OBJECT
    attributes stay host-side (opaque) and cannot participate in device expressions.
    """

    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @classmethod
    def parse(cls, name: str) -> "AttributeType":
        try:
            return cls(name.lower())
        except ValueError:
            raise ValueError(f"unknown attribute type: {name!r}")


@dataclass(frozen=True)
class Attribute:
    name: str
    type: AttributeType
    #: provenance marker: this LONG column is a forwarded raw-unionSet
    #: SET-SIZE projection (ops/selector.py host_set_slots) — the ONLY
    #: columns sizeOfSet() may read downstream. Rides auto-defined output
    #: stream definitions; never user-declarable.
    set_projection: bool = False

    def __repr__(self) -> str:
        return f"{self.name} {self.type.value}"


@dataclass(frozen=True)
class AbstractDefinition:
    """Base for all named definitions (reference: AbstractDefinition.java)."""

    id: str
    attributes: tuple[Attribute, ...] = ()
    annotations: tuple[Annotation, ...] = ()
    #: (line, column) of the `define ...` in the source text; metadata only —
    #: excluded from equality so AST comparisons ignore formatting
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)

    @property
    def attribute_names(self) -> tuple[str, ...]:
        return tuple(a.name for a in self.attributes)

    def attribute_index(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(f"attribute {name!r} not in {self.id} {self.attribute_names}")

    def attribute_type(self, name: str) -> AttributeType:
        return self.attributes[self.attribute_index(name)].type

    def annotation(self, name: str) -> Optional[Annotation]:
        for ann in self.annotations:
            if ann.name.lower() == name.lower():
                return ann
        return None


@dataclass(frozen=True)
class StreamDefinition(AbstractDefinition):
    """`define stream S (a int, b string, ...)`
    (reference: definition/StreamDefinition.java)."""


@dataclass(frozen=True)
class TableDefinition(AbstractDefinition):
    """`define table T (...)` — @PrimaryKey / @Index annotations select indexing
    (reference: definition/TableDefinition.java; holder selection in
    core/table/holder/EventHolderPasser via @PrimaryKey/@Index)."""

    @property
    def primary_keys(self) -> tuple[str, ...]:
        ann = self.annotation("PrimaryKey")
        return tuple(e.value for e in ann.elements) if ann else ()

    @property
    def indexes(self) -> tuple[str, ...]:
        ann = self.annotation("Index")
        return tuple(e.value for e in ann.elements) if ann else ()


@dataclass(frozen=True)
class WindowHandler:
    """A `#window:name(args)` or `#ns:fn(args)` handler reference used in
    definitions and FROM-clause chains (reference: api/execution/query/input/
    handler/Window.java, StreamFunction.java)."""

    namespace: str
    name: str
    # Expression args; typed as object to avoid circular import with expression.py.
    parameters: tuple[object, ...] = ()

    @property
    def full_name(self) -> str:
        return f"{self.namespace}:{self.name}" if self.namespace else self.name


@dataclass(frozen=True)
class WindowDefinition(AbstractDefinition):
    """`define window W (...) length(10) output all events`
    (reference: definition/WindowDefinition.java)."""

    window: Optional[WindowHandler] = None
    output_event_type: str = "all"  # current | expired | all


@dataclass(frozen=True)
class TriggerDefinition:
    """`define trigger T at every 5 sec | at 'cron' | at 'start'`
    (reference: definition/TriggerDefinition.java)."""

    id: str
    at_every_ms: Optional[int] = None  # periodic interval
    at_cron: Optional[str] = None  # cron expression
    at_start: bool = False
    annotations: tuple[Annotation, ...] = ()
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)


@dataclass(frozen=True)
class FunctionDefinition:
    """`define function f[lang] return type { body }`
    (reference: definition/FunctionDefinition.java). The TPU build supports
    language 'python' / 'jax': the body is compiled to a traced JAX callable."""

    id: str
    language: str
    return_type: AttributeType
    body: str
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)


# --- Incremental aggregation ---------------------------------------------------


class Duration(enum.Enum):
    """Time hierarchy for `define aggregation ... aggregate every sec...year`
    (reference: api/aggregation/TimePeriod.java Duration)."""

    SECONDS = "sec"
    MINUTES = "min"
    HOURS = "hours"
    DAYS = "days"
    MONTHS = "months"
    YEARS = "years"

    @classmethod
    def parse(cls, name: str) -> "Duration":
        n = name.lower().rstrip("s")
        aliases = {
            "sec": cls.SECONDS, "second": cls.SECONDS, "minute": cls.MINUTES,
            "min": cls.MINUTES, "hour": cls.HOURS, "day": cls.DAYS,
            "month": cls.MONTHS, "year": cls.YEARS,
        }
        if n in aliases:
            return aliases[n]
        raise ValueError(f"unknown duration: {name!r}")

    @property
    def order(self) -> int:
        return list(Duration).index(self)


#: Bucket length in milliseconds for fixed-length durations. MONTHS/YEARS need
#: calendar math (see aggregation/time.py) and are resolved per-timestamp.
DURATION_MS = {
    Duration.SECONDS: 1_000,
    Duration.MINUTES: 60_000,
    Duration.HOURS: 3_600_000,
    Duration.DAYS: 86_400_000,
}


@dataclass(frozen=True)
class AggregationDefinition:
    """`define aggregation A from S select ... group by ... aggregate by ts every
    sec ... year` (reference: definition/AggregationDefinition.java;
    runtime in core/aggregation/AggregationRuntime.java:82)."""

    id: str
    input_stream_id: str
    # selection is a Selector (execution.py); typed object to avoid circularity.
    selector: object = None
    group_by: tuple[object, ...] = ()
    aggregate_attribute: Optional[str] = None  # `aggregate by <attr>`; None = arrival ts
    durations: tuple[Duration, ...] = ()
    annotations: tuple[Annotation, ...] = ()
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)
