"""SiddhiApp: the top-level AST container (reference:
modules/siddhi-query-api/.../api/SiddhiApp.java)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .annotation import Annotation
from .definition import (
    AggregationDefinition,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from .execution import ExecutionElement, Partition, Query


@dataclass
class SiddhiApp:
    """Holds every definition + execution element of one app. Mutable during
    construction (the parser appends), treated as immutable afterwards."""

    stream_definitions: dict[str, StreamDefinition] = field(default_factory=dict)
    table_definitions: dict[str, TableDefinition] = field(default_factory=dict)
    window_definitions: dict[str, WindowDefinition] = field(default_factory=dict)
    trigger_definitions: dict[str, TriggerDefinition] = field(default_factory=dict)
    aggregation_definitions: dict[str, AggregationDefinition] = field(default_factory=dict)
    function_definitions: dict[str, FunctionDefinition] = field(default_factory=dict)
    execution_elements: list[ExecutionElement] = field(default_factory=list)
    annotations: list[Annotation] = field(default_factory=list)

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.stream_definitions[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.table_definitions[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.window_definitions[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.trigger_definitions[d.id] = d
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.aggregation_definitions[d.id] = d
        return self

    def define_function(self, d: FunctionDefinition) -> "SiddhiApp":
        self.function_definitions[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_elements.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_elements.append(p)
        return self

    def annotation(self, name: str):
        for ann in self.annotations:
            if ann.name.lower() == name.lower():
                return ann
        return None

    @property
    def queries(self) -> list[Query]:
        return [e for e in self.execution_elements if isinstance(e, Query)]

    @property
    def partitions(self) -> list[Partition]:
        return [e for e in self.execution_elements if isinstance(e, Partition)]

    @property
    def name(self) -> str:
        # `@app:name('X')` parses as an annotation literally named "app:name"
        # with one bare element (matching the reference's app-level annotation
        # addressing, SiddhiAppParser.java:91).
        ann = self.annotation("app:name")
        if ann and ann.elements:
            return ann.elements[0].value
        ann = self.annotation("app")
        if ann:
            v = ann.element("name")
            if v:
                return v
        return "SiddhiApp"

    def _check_unique(self, id_: str) -> None:
        for m in (self.stream_definitions, self.table_definitions,
                  self.window_definitions, self.trigger_definitions,
                  self.aggregation_definitions):
            if id_ in m:
                from ..errors import DuplicateDefinitionError
                raise DuplicateDefinitionError(f"{id_!r} is already defined")
