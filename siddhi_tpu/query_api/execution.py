"""Execution elements: queries, input streams, selection, output, patterns,
partitions (reference: modules/siddhi-query-api/.../api/execution/).

All pure data. The runtime planner (core/query_runtime.py) lowers these to
jitted `(state, batch) -> (state, outputs)` step functions.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from .annotation import Annotation
from .definition import WindowHandler
from .expression import Expression, Variable


# --- FROM clause: input streams ------------------------------------------------


@dataclass(frozen=True)
class StreamHandlerChain:
    """Handlers applied to one stream in arrival order: filters, stream
    functions, at most one window (reference: api/execution/query/input/handler/;
    ordering enforced by BasicSingleInputStream)."""

    filters: tuple[Expression, ...] = ()
    pre_window_functions: tuple[WindowHandler, ...] = ()
    window: Optional[WindowHandler] = None
    post_window_functions: tuple[WindowHandler, ...] = ()
    post_window_filters: tuple[Expression, ...] = ()


@dataclass(frozen=True)
class SingleInputStream:
    """`from S[filter]#fn(...)#window:w(...)` (reference:
    input/stream/SingleInputStream.java)."""

    stream_id: str
    alias: Optional[str] = None  # `from S as e`
    handlers: StreamHandlerChain = field(default_factory=StreamHandlerChain)
    is_inner: bool = False  # `#InnerStream` inside partitions
    is_fault: bool = False  # `!FaultStream`

    @property
    def reference_id(self) -> str:
        return self.alias or self.stream_id


class JoinType(enum.Enum):
    INNER = "join"
    LEFT_OUTER = "left outer join"
    RIGHT_OUTER = "right outer join"
    FULL_OUTER = "full outer join"


class EventTrigger(enum.Enum):
    """Which side's arrivals trigger join output (reference:
    JoinInputStream.EventTrigger)."""

    LEFT = "left"
    RIGHT = "right"
    ALL = "all"


@dataclass(frozen=True)
class JoinInputStream:
    """`from A#window.x() join B#window.y() on <cond>` (reference:
    input/stream/JoinInputStream.java; runtime core/query/input/stream/join/)."""

    left: SingleInputStream
    right: SingleInputStream
    join_type: JoinType = JoinType.INNER
    on: Optional[Expression] = None
    trigger: EventTrigger = EventTrigger.ALL
    within_ms: Optional[int] = None
    per: Optional[Expression] = None  # aggregation joins: `per "days"`


# --- Patterns / sequences (NFA AST) -------------------------------------------


@dataclass(frozen=True)
class StreamStateElement:
    """A single condition in a pattern: `e1=StockStream[price > 20]`
    (reference: input/state/StreamStateElement.java)."""

    stream: SingleInputStream


@dataclass(frozen=True)
class AbsentStreamStateElement:
    """`not StockStream[...] for 5 sec` (reference:
    input/state/AbsentStreamStateElement.java)."""

    stream: SingleInputStream
    waiting_time_ms: Optional[int] = None


@dataclass(frozen=True)
class CountStateElement:
    """`e1=S[...] <2:5>` (reference: input/state/CountStateElement.java).
    max == ANY (-1) means unbounded."""

    element: StreamStateElement
    min_count: int
    max_count: int  # -1 = unbounded

    ANY = -1


@dataclass(frozen=True)
class LogicalStateElement:
    """`A and B`, `A or B` (reference: input/state/LogicalStateElement.java)."""

    left: object  # StateElement
    logical_type: str  # "and" | "or"
    right: object  # StateElement


@dataclass(frozen=True)
class NextStateElement:
    """`A -> B` (pattern) or `A , B` (sequence) (reference:
    input/state/NextStateElement.java)."""

    state: object  # StateElement
    next: object  # StateElement


@dataclass(frozen=True)
class EveryStateElement:
    """`every (A -> B)` — re-arm on match (reference:
    input/state/EveryStateElement.java)."""

    state: object  # StateElement


StateElement = (
    StreamStateElement | AbsentStreamStateElement | CountStateElement |
    LogicalStateElement | NextStateElement | EveryStateElement
)


class StateType(enum.Enum):
    PATTERN = "pattern"  # `->` skip-till-any-match
    SEQUENCE = "sequence"  # `,` strict contiguity


@dataclass(frozen=True)
class StateInputStream:
    """`from every e1=A -> e2=B within 5 sec` (reference:
    input/stream/StateInputStream.java)."""

    state_type: StateType
    state: StateElement
    within_ms: Optional[int] = None


InputStream = SingleInputStream | JoinInputStream | StateInputStream


# --- SELECT clause -------------------------------------------------------------


@dataclass(frozen=True)
class OutputAttribute:
    """`expr as name` (reference: selection/OutputAttribute.java)."""

    rename: str
    expression: Expression


class OrderByOrder(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclass(frozen=True)
class OrderByAttribute:
    variable: Variable
    order: OrderByOrder = OrderByOrder.ASC


@dataclass(frozen=True)
class Selector:
    """SELECT + GROUP BY + HAVING + ORDER BY + LIMIT/OFFSET (reference:
    selection/Selector.java; runtime core/query/selector/QuerySelector.java:44)."""

    attributes: tuple[OutputAttribute, ...] = ()  # empty = select *
    group_by: tuple[Variable, ...] = ()
    having: Optional[Expression] = None
    order_by: tuple[OrderByAttribute, ...] = ()
    limit: Optional[int] = None
    offset: Optional[int] = None

    @property
    def is_select_all(self) -> bool:
        return not self.attributes


# --- Output --------------------------------------------------------------------


class OutputEventType(enum.Enum):
    """`insert [current|expired|all] events into ...` (reference:
    api/execution/query/output/stream/OutputStream.OutputEventType)."""

    CURRENT = "current events"
    EXPIRED = "expired events"
    ALL = "all events"


class OutputAction(enum.Enum):
    INSERT = "insert"
    DELETE = "delete"
    UPDATE = "update"
    UPDATE_OR_INSERT = "update or insert"
    RETURN = "return"


@dataclass(frozen=True)
class UpdateSetAttribute:
    table_variable: Variable
    expression: Expression


@dataclass(frozen=True)
class OutputStream:
    """Terminal action of a query (reference:
    api/execution/query/output/stream/*.java)."""

    action: OutputAction
    target_id: Optional[str] = None  # None for RETURN
    event_type: OutputEventType = OutputEventType.CURRENT
    on_condition: Optional[Expression] = None  # delete/update ... on <cond>
    set_attributes: tuple[UpdateSetAttribute, ...] = ()
    is_fault: bool = False  # `insert into !Stream`
    is_inner: bool = False  # `insert into #Inner` (partition-scoped stream)


class OutputRateType(enum.Enum):
    ALL = "all"
    FIRST = "first"
    LAST = "last"
    SNAPSHOT = "snapshot"


@dataclass(frozen=True)
class OutputRate:
    """`output [all|first|last] every 5 sec / every 3 events / snapshot every ...`
    (reference: api/execution/query/output/ratelimit/)."""

    type: OutputRateType = OutputRateType.ALL
    time_ms: Optional[int] = None
    event_count: Optional[int] = None


# --- Query & partition ---------------------------------------------------------


@dataclass(frozen=True)
class Query:
    """One continuous query (reference: api/execution/query/Query.java)."""

    input_stream: InputStream
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = field(default_factory=lambda: OutputStream(OutputAction.RETURN))
    output_rate: Optional[OutputRate] = None
    annotations: tuple[Annotation, ...] = ()
    #: (line, column) of the `from ...` clause; metadata only, never compared
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)

    @property
    def name(self) -> Optional[str]:
        for ann in self.annotations:
            if ann.name.lower() == "info":
                return ann.element("name")
        return None


@dataclass(frozen=True)
class ValuePartitionType:
    """`partition with (attr of Stream)` (reference:
    api/execution/partition/ValuePartitionType.java)."""

    stream_id: str
    expression: Expression


@dataclass(frozen=True)
class RangePartitionProperty:
    partition_key: str
    condition: Expression


@dataclass(frozen=True)
class RangePartitionType:
    """`partition with (cond as 'key' or ... of Stream)` (reference:
    api/execution/partition/RangePartitionType.java)."""

    stream_id: str
    ranges: tuple[RangePartitionProperty, ...]


PartitionType = ValuePartitionType | RangePartitionType


@dataclass(frozen=True)
class Partition:
    """`partition with (...) begin <queries> end` (reference:
    api/execution/partition/Partition.java; runtime
    core/partition/PartitionRuntimeImpl.java:75)."""

    partition_types: tuple[PartitionType, ...]
    queries: tuple[Query, ...]
    annotations: tuple[Annotation, ...] = ()
    loc: Optional[tuple] = field(default=None, compare=False, repr=False)


@dataclass(frozen=True)
class OnDemandQuery:
    """Ad-hoc pull query against a table/window/aggregation (reference:
    api/execution/query/OnDemandQuery.java)."""

    input_store_id: str
    on_condition: Optional[Expression] = None
    within_range: Optional[tuple[Expression, Expression]] = None  # aggregations
    per: Optional[Expression] = None
    selector: Selector = field(default_factory=Selector)
    action: OutputAction = OutputAction.RETURN
    set_attributes: tuple[UpdateSetAttribute, ...] = ()
    target_id: Optional[str] = None


ExecutionElement = Query | Partition
