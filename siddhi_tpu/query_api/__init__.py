"""Query object model (AST) for SiddhiQL — the TPU build's equivalent of the
reference's siddhi-query-api module. Pure frozen dataclasses; constructed either
by the compiler (siddhi_tpu.compiler) or programmatically."""

from .annotation import Annotation, Element
from .definition import (
    AbstractDefinition,
    AggregationDefinition,
    Attribute,
    AttributeType,
    Duration,
    DURATION_MS,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
    WindowHandler,
)
from .expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    In,
    IsNull,
    MathExpression,
    MathOp,
    Not,
    Or,
    Variable,
    const,
    time_constant_ms,
)
from .execution import (
    AbsentStreamStateElement,
    CountStateElement,
    EventTrigger,
    EveryStateElement,
    InputStream,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    NextStateElement,
    OnDemandQuery,
    OrderByAttribute,
    OrderByOrder,
    OutputAction,
    OutputAttribute,
    OutputEventType,
    OutputRate,
    OutputRateType,
    OutputStream,
    Partition,
    PartitionType,
    Query,
    RangePartitionProperty,
    RangePartitionType,
    Selector,
    SingleInputStream,
    StateInputStream,
    StateType,
    StreamHandlerChain,
    StreamStateElement,
    UpdateSetAttribute,
    ValuePartitionType,
)
from .siddhi_app import SiddhiApp

__all__ = [n for n in dir() if not n.startswith("_")]
