"""Generic `@name(key='value', ...)` annotations attachable to any definition or
query (reference: modules/siddhi-query-api/.../api/annotation/Annotation.java,
Element.java)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Element:
    key: Optional[str]
    value: str


@dataclass(frozen=True)
class Annotation:
    name: str
    elements: tuple[Element, ...] = ()
    nested: tuple["Annotation", ...] = ()

    def element(self, key: Optional[str] = None, default: Optional[str] = None) -> Optional[str]:
        """Value of the element with `key` (None matches the bare positional value)."""
        for e in self.elements:
            if (e.key.lower() if e.key else None) == (key.lower() if key else None):
                return e.value
        return default

    def nested_annotation(self, name: str) -> Optional["Annotation"]:
        for a in self.nested:
            if a.name.lower() == name.lower():
                return a
        return None
