"""Expression AST (reference: modules/siddhi-query-api/.../api/expression/).

Where the reference walks this tree per event with an interpreter
(core/util/parser/ExpressionParser.java:225 building monomorphic
ExpressionExecutor objects), the TPU build traces it ONCE into a jitted JAX
function over columnar batches (ops/expr_compile.py). The AST is therefore pure
data — frozen dataclasses with no behavior.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Union


class Expression:
    """Marker base class for all expression nodes."""

    __slots__ = ()


# --- Constants -----------------------------------------------------------------


@dataclass(frozen=True)
class Constant(Expression):
    """Typed literal (reference: api/expression/constant/*). `value` is a Python
    scalar; `type_name` one of int/long/float/double/bool/string/time."""

    value: object
    type_name: str


def time_constant_ms(value: float, unit: str) -> Constant:
    """`5 sec`, `1 min`, ... → milliseconds (reference: constant/TimeConstant.java)."""
    ms = {
        "millisec": 1, "milliseconds": 1, "sec": 1000, "second": 1000,
        "min": 60_000, "minute": 60_000, "hour": 3_600_000,
        "day": 86_400_000, "week": 604_800_000, "month": 2_592_000_000,
        "year": 31_536_000_000,
    }
    key = unit.lower().rstrip("s") if unit.lower() not in ("milliseconds", "millisec") else "millisec"
    if key not in ms:
        raise ValueError(f"unknown time unit {unit!r}")
    return Constant(int(value * ms[key]), "long")


# --- Variables -----------------------------------------------------------------


@dataclass(frozen=True)
class Variable(Expression):
    """`[stream.]attr` optionally with a stream index for patterns: `e1[0].price`
    (reference: api/expression/Variable.java)."""

    attribute: str
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None  # pattern count-group element index
    is_last: bool = False  # e1[last]


# --- Math ----------------------------------------------------------------------


class MathOp(enum.Enum):
    ADD = "+"
    SUBTRACT = "-"
    MULTIPLY = "*"
    DIVIDE = "/"
    MOD = "%"


@dataclass(frozen=True)
class MathExpression(Expression):
    op: MathOp
    left: Expression
    right: Expression


# --- Conditions ----------------------------------------------------------------


class CompareOp(enum.Enum):
    EQUAL = "=="
    NOT_EQUAL = "!="
    GREATER_THAN = ">"
    GREATER_THAN_EQUAL = ">="
    LESS_THAN = "<"
    LESS_THAN_EQUAL = "<="


@dataclass(frozen=True)
class Compare(Expression):
    left: Expression
    op: CompareOp
    right: Expression


@dataclass(frozen=True)
class And(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class Or(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class Not(Expression):
    expression: Expression


@dataclass(frozen=True)
class IsNull(Expression):
    """`x is null` — with columnar batches this tests the per-attribute validity
    mask (reference: api/expression/condition/IsNull.java). The stream variant
    (`e2 is null` in patterns) carries stream_id only."""

    expression: Optional[Expression] = None
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None


@dataclass(frozen=True)
class In(Expression):
    """`<cond> in TableName` — membership test against a table
    (reference: api/expression/condition/In.java)."""

    expression: Expression
    source_id: str


# --- Functions -----------------------------------------------------------------


@dataclass(frozen=True)
class AttributeFunction(Expression):
    """`[ns:]name(arg, ...)` — scalar function OR aggregator; the selector parser
    decides which by registry lookup, mirroring the reference's aggregator
    detection (ExpressionParser.java:462)."""

    namespace: str
    name: str
    parameters: tuple[Expression, ...] = ()

    @property
    def full_name(self) -> str:
        return f"{self.namespace}:{self.name}" if self.namespace else self.name


ExpressionLike = Union[Expression, int, float, bool, str]


def const(value: ExpressionLike) -> Expression:
    """Coerce a Python literal into a Constant node (builder-API convenience)."""
    if isinstance(value, Expression):
        return value
    if isinstance(value, bool):
        return Constant(value, "bool")
    if isinstance(value, int):
        return Constant(value, "long" if abs(value) > 2**31 - 1 else "int")
    if isinstance(value, float):
        return Constant(value, "double")
    if isinstance(value, str):
        return Constant(value, "string")
    raise TypeError(f"cannot make a constant from {value!r}")
