/* colring_core.h — the lock-free columnar ring's claim/publish/consume
 * protocol, extracted from columnar.c so that a standalone pthreads stress
 * harness (colring_stress.c) can compile the EXACT same code under
 * -fsanitize=thread / address / undefined. The Python extension keeps all
 * arg parsing, Py_buffer handling, and GIL management in its wrappers and
 * delegates every atomic to these inline functions — the protocol is
 * machine-checked, not argued-in-comments.
 *
 * Protocol (Disruptor-style multi-producer, single-consumer):
 *   - producers claim a contiguous run of n slots with ONE CAS on `head`
 *     (crc_claim); claim order IS delivery order, so parallel out-of-order
 *     writers stay deterministic downstream;
 *   - each slot is published by a release store of `index + 1` into its
 *     cache-line-padded seq entry (crc_publish) AFTER the payload is
 *     written — the release pairs with the consumer's acquire loads;
 *   - the single consumer counts the contiguous published prefix with
 *     acquire loads (crc_poll), copies the payload out, then retires the
 *     run (crc_consume): seq resets are relaxed (only producers that
 *     already observed the new tail can reuse the slot), the tail bump is
 *     a release store (it licenses producers to overwrite the slots).
 *
 * Pure C11 + stdatomic; no Python.h. The owner allocates the seq array
 * (cap entries, zero-initialised) and hands it to crc_init.
 */

#ifndef SIDDHI_COLRING_CORE_H
#define SIDDHI_COLRING_CORE_H

#include <stdatomic.h>
#include <stddef.h>

/* Slot sequence entries are cache-line padded: adjacent slots are
 * published by different producer threads, and false sharing on the seq
 * array is the classic scalability cliff for exactly this structure. */
typedef struct {
    atomic_size_t v;
    char pad[64 - sizeof(atomic_size_t)];
} crc_seq;

typedef struct {
    size_t cap;               /* power of two */
    size_t mask;
    crc_seq *seq;             /* published when seq[i & mask].v == i + 1 */
    atomic_size_t head;       /* next slot to claim (producers, CAS) */
    char pad1[64 - sizeof(atomic_size_t)];
    atomic_size_t tail;       /* next slot to read (single consumer) */
    char pad2[64 - sizeof(atomic_size_t)];
    atomic_size_t hwm;        /* claimed-depth high-water mark */
} crc_ring;

/* cap must be a power of two; seq must hold cap zero-initialised entries
 * and stay alive as long as the ring. */
static inline void
crc_init(crc_ring *r, crc_seq *seq, size_t cap)
{
    r->cap = cap;
    r->mask = cap - 1;
    r->seq = seq;
    atomic_init(&r->head, 0);
    atomic_init(&r->tail, 0);
    atomic_init(&r->hwm, 0);
}

/* Claim n contiguous slots; returns the start index, or -1 when the ring
 * lacks n free slots (all-or-nothing; the caller spins/backpressures).
 * The successful CAS is acq_rel: the acquire half orders the claim after
 * the tail observation, the release half makes the claim visible before
 * any payload store the producer issues next. */
static inline ptrdiff_t
crc_claim(crc_ring *r, size_t n)
{
    size_t h = atomic_load_explicit(&r->head, memory_order_relaxed);
    for (;;) {
        size_t t = atomic_load_explicit(&r->tail, memory_order_acquire);
        if (h + n - t > r->cap)
            return -1; /* insufficient free space */
        if (atomic_compare_exchange_weak_explicit(
                &r->head, &h, h + n,
                memory_order_acq_rel, memory_order_relaxed)) {
            size_t depth = h + n - t;
            size_t hwm = atomic_load_explicit(&r->hwm, memory_order_relaxed);
            while (depth > hwm &&
                   !atomic_compare_exchange_weak_explicit(
                       &r->hwm, &hwm, depth,
                       memory_order_relaxed, memory_order_relaxed))
                ;
            return (ptrdiff_t)h;
        }
    }
}

/* Publish one claimed run. MUST run after the payload for [start,
 * start + n) is fully written: the per-slot release stores are what make
 * those plain payload writes visible to the consumer's acquire loads. */
static inline void
crc_publish(crc_ring *r, size_t start, size_t n)
{
    for (size_t i = 0; i < n; i++)
        atomic_store_explicit(&r->seq[(start + i) & r->mask].v,
                              start + i + 1, memory_order_release);
}

/* Single consumer: length of the contiguous published prefix at the
 * current tail, capped at max_n. After this returns k, the payload of
 * slots [tail, tail + k) is safe to read (acquire loads above). */
static inline size_t
crc_poll(crc_ring *r, size_t max_n)
{
    size_t t = atomic_load_explicit(&r->tail, memory_order_relaxed);
    size_t n = 0;
    while (n < max_n &&
           atomic_load_explicit(&r->seq[(t + n) & r->mask].v,
                                memory_order_acquire) == t + n + 1)
        n++;
    return n;
}

/* Single consumer: retire n slots previously returned by crc_poll. Seq
 * resets can be relaxed — a producer only reuses a slot after observing
 * the released tail bump, which orders the reset before the reuse. */
static inline void
crc_consume(crc_ring *r, size_t n)
{
    size_t t = atomic_load_explicit(&r->tail, memory_order_relaxed);
    for (size_t i = 0; i < n; i++)
        atomic_store_explicit(&r->seq[(t + i) & r->mask].v, 0,
                              memory_order_relaxed);
    atomic_store_explicit(&r->tail, t + n, memory_order_release);
}

/* Claimed, unconsumed depth (approximate under concurrent producers;
 * includes claimed-but-unwritten runs). */
static inline size_t
crc_size(const crc_ring *r)
{
    return atomic_load_explicit(&((crc_ring *)r)->head,
                                memory_order_relaxed) -
           atomic_load_explicit(&((crc_ring *)r)->tail,
                                memory_order_relaxed);
}

static inline size_t
crc_hwm(const crc_ring *r)
{
    return atomic_load_explicit(&((crc_ring *)r)->hwm,
                                memory_order_relaxed);
}

#endif /* SIDDHI_COLRING_CORE_H */
