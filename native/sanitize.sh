#!/bin/sh
# Build and run the colring stress harness under ThreadSanitizer, then
# again under AddressSanitizer + UBSan. Any data race, leak, UB, or oracle
# failure exits non-zero — this is the tier-1 CI gate that keeps the
# lock-free ring protocol (native/colring_core.h) machine-checked.
#
#     native/sanitize.sh [producers] [items] [capacity] [max_run]
#
# Defaults are CI-sized (a few seconds per sanitizer). CC overrides gcc.
set -eu
cd "$(dirname "$0")"
CC="${CC:-gcc}"
OUT="${TMPDIR:-/tmp}/siddhi-colring-sanitize"
mkdir -p "$OUT"

PRODUCERS="${1:-4}"
ITEMS="${2:-200000}"
CAPACITY="${3:-1024}"
MAX_RUN="${4:-17}"

echo "== tsan: $CC -fsanitize=thread =="
"$CC" -std=c11 -O1 -g -pthread -fsanitize=thread \
    -o "$OUT/colring_stress_tsan" colring_stress.c
TSAN_OPTIONS="halt_on_error=1 exitcode=66" \
    "$OUT/colring_stress_tsan" "$PRODUCERS" "$ITEMS" "$CAPACITY" "$MAX_RUN"

echo "== asan+ubsan: $CC -fsanitize=address,undefined =="
"$CC" -std=c11 -O1 -g -pthread -fsanitize=address,undefined \
    -fno-sanitize-recover=all \
    -o "$OUT/colring_stress_asan" colring_stress.c
"$OUT/colring_stress_asan" "$PRODUCERS" "$ITEMS" "$CAPACITY" "$MAX_RUN"

echo "sanitize: colring stress clean under tsan and asan+ubsan"
