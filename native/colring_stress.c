/* colring_stress.c — standalone multi-producer/single-consumer stress for
 * the lock-free columnar ring protocol (colring_core.h), built to run under
 * -fsanitize=thread (and address/undefined): the single-CAS claim +
 * release-store publish + acquire-load consume protocol is machine-checked
 * against real concurrent producers, not argued in comments.
 *
 *     gcc -std=c11 -O1 -g -fsanitize=thread colring_stress.c -lpthread
 *     ./a.out [producers] [items_per_producer] [capacity] [max_run]
 *
 * Producers claim runs of random length, write a payload derived from each
 * slot's GLOBAL index into plain (non-atomic) arrays, then publish. The
 * consumer polls the contiguous published prefix, checks every payload
 * against the same index function, and retires the run. Oracles:
 *
 *   conservation    — consumed slot count == producers * items_per_producer
 *   data integrity  — payload(g) matches for every consumed global index g
 *                     (catches torn/unpublished reads the instant the
 *                     release/acquire pairing is wrong, even without TSan)
 *   checksum        — sum of consumed payloads == closed-form expected sum
 *   quiescence      — ring empty at the end; high-water mark <= capacity
 *
 * Exit 0 when every oracle holds (and, under a sanitizer, no report fired:
 * TSan/ASan make failures exit non-zero on their own).
 */

#include <inttypes.h>
#include <pthread.h>
#include <sched.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "colring_core.h"

/* Payload columns, mirroring the Python extension's layout: an int64
 * timestamp column plus one int32 data column. Both are PLAIN memory on
 * purpose — their cross-thread visibility must come entirely from the
 * protocol's release/acquire pairing, which is the property under test. */
static int64_t *g_ts;
static int32_t *g_col;
static crc_ring g_ring;

static inline int64_t
payload_ts(size_t g)
{
    return (int64_t)(g * UINT64_C(2654435761) ^ UINT64_C(0x9E3779B97F4A7C15));
}

static inline int32_t
payload_col(size_t g)
{
    return (int32_t)(uint32_t)(g * UINT32_C(0x85EBCA6B) + UINT32_C(0xC2B2AE35));
}

typedef struct {
    size_t items;       /* slots this producer must publish */
    size_t max_run;
    unsigned seed;
    size_t full_spins;  /* backpressure encounters (ring-full claims) */
} producer_arg;

static void *
producer_main(void *argp)
{
    producer_arg *a = (producer_arg *)argp;
    unsigned rng = a->seed;
    size_t left = a->items;
    while (left > 0) {
        rng = rng * 1103515245u + 12345u;
        size_t n = 1 + (rng >> 16) % a->max_run;
        if (n > left)
            n = left;
        ptrdiff_t start = crc_claim(&g_ring, n);
        if (start < 0) {
            a->full_spins++;
            sched_yield();      /* backpressure: consumer must drain */
            continue;
        }
        for (size_t i = 0; i < n; i++) {
            size_t g = (size_t)start + i;
            size_t s = g & g_ring.mask;
            g_ts[s] = payload_ts(g);
            g_col[s] = payload_col(g);
        }
        crc_publish(&g_ring, (size_t)start, n);
        left -= n;
    }
    return NULL;
}

typedef struct {
    size_t total;       /* slots to consume before stopping */
    size_t consumed;
    uint64_t checksum;
    size_t integrity_errors;
} consumer_arg;

static void *
consumer_main(void *argp)
{
    consumer_arg *a = (consumer_arg *)argp;
    while (a->consumed < a->total) {
        size_t n = crc_poll(&g_ring, a->total - a->consumed);
        if (n == 0) {
            sched_yield();
            continue;
        }
        size_t t = a->consumed;     /* == ring tail: single consumer */
        for (size_t i = 0; i < n; i++) {
            size_t g = t + i;
            size_t s = g & g_ring.mask;
            if (g_ts[s] != payload_ts(g) || g_col[s] != payload_col(g)) {
                a->integrity_errors++;
                fprintf(stderr,
                        "integrity: slot %zu (global %zu): ts=%" PRId64
                        " col=%" PRId32 "\n", s, g, g_ts[s], g_col[s]);
            }
            a->checksum += (uint64_t)g_col[s] & 0xFFFFFFFFu;
        }
        crc_consume(&g_ring, n);
        a->consumed += n;
    }
    return NULL;
}

int
main(int argc, char **argv)
{
    size_t producers = argc > 1 ? (size_t)atol(argv[1]) : 4;
    size_t items = argc > 2 ? (size_t)atol(argv[2]) : 200000;
    size_t cap = argc > 3 ? (size_t)atol(argv[3]) : 1024;
    size_t max_run = argc > 4 ? (size_t)atol(argv[4]) : 17;
    if (producers < 1 || items < 1 || max_run < 1 ||
        (cap & (cap - 1)) != 0 || max_run > cap) {
        fprintf(stderr, "usage: %s [producers>=1] [items>=1] "
                        "[capacity:pow2] [max_run<=capacity]\n", argv[0]);
        return 2;
    }

    crc_seq *seq = calloc(cap, sizeof(crc_seq));
    g_ts = malloc(cap * sizeof(int64_t));
    g_col = malloc(cap * sizeof(int32_t));
    if (!seq || !g_ts || !g_col) {
        fprintf(stderr, "alloc failed\n");
        return 2;
    }
    crc_init(&g_ring, seq, cap);

    size_t total = producers * items;
    uint64_t expect_sum = 0;
    for (size_t g = 0; g < total; g++)
        expect_sum += (uint64_t)payload_col(g) & 0xFFFFFFFFu;

    pthread_t cons;
    consumer_arg ca = { .total = total };
    pthread_t *prod = calloc(producers, sizeof(pthread_t));
    producer_arg *pa = calloc(producers, sizeof(producer_arg));
    if (!prod || !pa) {
        fprintf(stderr, "alloc failed\n");
        return 2;
    }
    pthread_create(&cons, NULL, consumer_main, &ca);
    for (size_t p = 0; p < producers; p++) {
        pa[p].items = items;
        pa[p].max_run = max_run;
        pa[p].seed = (unsigned)(0xA5A5u + 977u * p);
        pthread_create(&prod[p], NULL, producer_main, &pa[p]);
    }
    for (size_t p = 0; p < producers; p++)
        pthread_join(prod[p], NULL);
    pthread_join(cons, NULL);

    size_t full_spins = 0;
    for (size_t p = 0; p < producers; p++)
        full_spins += pa[p].full_spins;

    int bad = 0;
    if (ca.consumed != total) {
        fprintf(stderr, "conservation: consumed %zu != produced %zu\n",
                ca.consumed, total);
        bad = 1;
    }
    if (ca.integrity_errors) {
        fprintf(stderr, "integrity: %zu bad slots\n", ca.integrity_errors);
        bad = 1;
    }
    if (ca.checksum != expect_sum) {
        fprintf(stderr, "checksum: got %" PRIu64 " want %" PRIu64 "\n",
                ca.checksum, expect_sum);
        bad = 1;
    }
    if (crc_size(&g_ring) != 0) {
        fprintf(stderr, "quiescence: ring depth %zu != 0\n",
                crc_size(&g_ring));
        bad = 1;
    }
    if (crc_hwm(&g_ring) > cap) {
        fprintf(stderr, "hwm %zu exceeds capacity %zu\n",
                crc_hwm(&g_ring), cap);
        bad = 1;
    }

    printf("colring stress: %zu producers x %zu items, cap %zu, "
           "max_run %zu -> consumed %zu, hwm %zu, ring-full spins %zu: %s\n",
           producers, items, cap, max_run, ca.consumed,
           crc_hwm(&g_ring), full_spins, bad ? "FAIL" : "OK");
    free(prod);
    free(pa);
    free(seq);
    free(g_ts);
    free(g_col);
    return bad;
}
