/* _siddhi_native — C hot path for host-side event marshalling.
 *
 * Role in the framework: the TPU compute path is JAX/XLA; the host runtime
 * around it (ingestion marshalling, string interning) is native, mirroring
 * how the reference's performance-critical event plumbing is engineered
 * (reference: core/event/stream/converter/ — ZeroStreamEventConverter etc.,
 * and the Disruptor ring's event translation, StreamJunction.java:149-182).
 *
 * encode_rows() converts a Python list of row tuples into pre-allocated
 * columnar numpy buffers (via the buffer protocol — no numpy C-API
 * dependency), interning strings through the SAME dict/list pair that backs
 * the Python StringTable, so native and Python encode paths share one code
 * space and snapshot/restore stays unchanged.
 *
 * Type codes (one byte per attribute):
 *   'b' bool -> int8 buffer      'i' int -> int32
 *   'l' long -> int64            'f' float -> float32
 *   'd' double -> float64        's' string -> int32 (interned code)
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

/* Intern one string through (to_code: dict, to_str: list); returns code or -1
 * on error. None encodes as 0 (null). `transient` (may be NULL) is the
 * StringTable's transient-code dict: a LIVE transient string (a uuid coming
 * back from a client) must round-trip to its transient code, or device
 * equality against stored uuid columns would never match — and permanently
 * interning it would shadow the transient code for every later encode(). */
static int32_t
intern_string(PyObject *value, PyObject *to_code, PyObject *to_str,
              PyObject *transient)
{
    if (value == Py_None)
        return 0;
    PyObject *existing = PyDict_GetItemWithError(to_code, value);
    if (existing != NULL)
        return (int32_t)PyLong_AsLong(existing);
    if (PyErr_Occurred())
        return -1;
    if (transient != NULL && transient != Py_None) {
        existing = PyDict_GetItemWithError(transient, value);
        if (existing != NULL)
            return (int32_t)PyLong_AsLong(existing);
        if (PyErr_Occurred())
            return -1;
    }
    Py_ssize_t code = PyList_GET_SIZE(to_str);
    PyObject *code_obj = PyLong_FromSsize_t(code);
    if (code_obj == NULL)
        return -1;
    if (PyDict_SetItem(to_code, value, code_obj) < 0 ||
        PyList_Append(to_str, value) < 0) {
        Py_DECREF(code_obj);
        return -1;
    }
    Py_DECREF(code_obj);
    return (int32_t)code;
}

/* encode_rows(rows, typecodes: bytes, columns: tuple[memoryview-able],
 *             tables: tuple[(dict, list) | None], nulls: tuple[float|int]) */
static PyObject *
encode_rows(PyObject *self, PyObject *args)
{
    PyObject *rows, *typecodes_obj, *columns, *tables, *nulls;
    if (!PyArg_ParseTuple(args, "OSOOO", &rows, &typecodes_obj, &columns,
                          &tables, &nulls))
        return NULL;

    const char *typecodes = PyBytes_AS_STRING(typecodes_obj);
    Py_ssize_t n_cols = PyBytes_GET_SIZE(typecodes_obj);

    if (!PyTuple_Check(columns) || PyTuple_GET_SIZE(columns) < n_cols ||
        !PyTuple_Check(tables) || PyTuple_GET_SIZE(tables) < n_cols ||
        !PyTuple_Check(nulls) || PyTuple_GET_SIZE(nulls) < n_cols) {
        PyErr_SetString(PyExc_TypeError,
                        "columns/tables/nulls must be tuples of arity >= "
                        "len(typecodes)");
        return NULL;
    }

    PyObject *rows_fast = PySequence_Fast(rows, "rows must be a sequence");
    if (rows_fast == NULL)
        return NULL;
    Py_ssize_t n_rows = PySequence_Fast_GET_SIZE(rows_fast);

    /* acquire writable buffers for every column */
    Py_buffer *bufs = PyMem_Calloc((size_t)n_cols, sizeof(Py_buffer));
    if (bufs == NULL) {
        Py_DECREF(rows_fast);
        return PyErr_NoMemory();
    }
    Py_ssize_t acquired = 0;
    PyObject *result = NULL;
    for (; acquired < n_cols; acquired++) {
        PyObject *col = PyTuple_GET_ITEM(columns, acquired);
        if (PyObject_GetBuffer(col, &bufs[acquired],
                               PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
            goto done;
        /* capacity check: a short buffer would mean silent heap corruption
         * where the pure-Python fallback raises IndexError */
        static const Py_ssize_t width[128] = {
            ['b'] = 1, ['i'] = 4, ['l'] = 8, ['f'] = 4, ['d'] = 8, ['s'] = 4};
        char tc = typecodes[acquired];
        Py_ssize_t w = ((unsigned char)tc < 128) ? width[(int)tc] : 0;
        if (w == 0) {
            PyErr_Format(PyExc_ValueError, "bad type code %c", tc);
            acquired++; /* this buffer was acquired; release it in done */
            goto done;
        }
        if (bufs[acquired].len < n_rows * w) {
            PyErr_Format(PyExc_ValueError,
                         "column %zd buffer too small: %zd bytes for %zd "
                         "rows of width %zd", acquired, bufs[acquired].len,
                         n_rows, w);
            acquired++;
            goto done;
        }
        if (tc == 's') {
            PyObject *pair = PyTuple_GET_ITEM(tables, acquired);
            if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) < 2 ||
                PyTuple_GET_SIZE(pair) > 3 ||
                !PyDict_Check(PyTuple_GET_ITEM(pair, 0)) ||
                !PyList_Check(PyTuple_GET_ITEM(pair, 1)) ||
                (PyTuple_GET_SIZE(pair) == 3 &&
                 !PyDict_Check(PyTuple_GET_ITEM(pair, 2)))) {
                PyErr_Format(PyExc_TypeError,
                             "tables[%zd] must be (dict, list[, transient "
                             "dict]) for a string column", acquired);
                acquired++;
                goto done;
            }
        }
    }

    for (Py_ssize_t r = 0; r < n_rows; r++) {
        PyObject *row = PySequence_Fast_GET_ITEM(rows_fast, r);
        PyObject *row_fast = PySequence_Fast(row, "row must be a sequence");
        if (row_fast == NULL)
            goto done;
        if (PySequence_Fast_GET_SIZE(row_fast) < n_cols) {
            Py_DECREF(row_fast);
            PyErr_Format(PyExc_ValueError,
                         "row %zd has fewer than %zd values", r, n_cols);
            goto done;
        }
        for (Py_ssize_t c = 0; c < n_cols; c++) {
            PyObject *v = PySequence_Fast_GET_ITEM(row_fast, c);
            void *data = bufs[c].buf;
            char tc = typecodes[c];
            if (tc == 's') {
                PyObject *pair = PyTuple_GET_ITEM(tables, c);
                int32_t code = intern_string(
                    v, PyTuple_GET_ITEM(pair, 0), PyTuple_GET_ITEM(pair, 1),
                    PyTuple_GET_SIZE(pair) == 3 ? PyTuple_GET_ITEM(pair, 2)
                                                : NULL);
                if (code < 0 && PyErr_Occurred()) {
                    Py_DECREF(row_fast);
                    goto done;
                }
                ((int32_t *)data)[r] = code;
                continue;
            }
            int is_null = (v == Py_None);
            if (is_null)
                v = PyTuple_GET_ITEM(nulls, c);
            switch (tc) {
            case 'b': {
                int x = PyObject_IsTrue(v);
                if (x < 0) { Py_DECREF(row_fast); goto done; }
                ((int8_t *)data)[r] = (int8_t)x;
                break;
            }
            case 'i': {
                long x = PyLong_AsLong(v);
                if (x == -1 && PyErr_Occurred()) { Py_DECREF(row_fast); goto done; }
                ((int32_t *)data)[r] = (int32_t)x;
                break;
            }
            case 'l': {
                long long x = PyLong_AsLongLong(v);
                if (x == -1 && PyErr_Occurred()) { Py_DECREF(row_fast); goto done; }
                ((int64_t *)data)[r] = (int64_t)x;
                break;
            }
            case 'f': {
                double x = PyFloat_AsDouble(v);
                if (x == -1.0 && PyErr_Occurred()) { Py_DECREF(row_fast); goto done; }
                ((float *)data)[r] = (float)x;
                break;
            }
            case 'd': {
                double x = PyFloat_AsDouble(v);
                if (x == -1.0 && PyErr_Occurred()) { Py_DECREF(row_fast); goto done; }
                ((double *)data)[r] = x;
                break;
            }
            default:
                Py_DECREF(row_fast);
                PyErr_Format(PyExc_ValueError, "bad type code %c", tc);
                goto done;
            }
        }
        Py_DECREF(row_fast);
    }
    result = Py_NewRef(Py_None);

done:
    for (Py_ssize_t i = 0; i < acquired; i++)
        PyBuffer_Release(&bufs[i]);
    PyMem_Free(bufs);
    Py_DECREF(rows_fast);
    return result;
}

/* fill_ts(ts_list, out: int64 buffer, n_pad) — timestamps + monotone pad */
static PyObject *
fill_ts(PyObject *self, PyObject *args)
{
    PyObject *ts_list, *out;
    Py_ssize_t n_pad;
    if (!PyArg_ParseTuple(args, "OOn", &ts_list, &out, &n_pad))
        return NULL;
    PyObject *fast = PySequence_Fast(ts_list, "ts must be a sequence");
    if (fast == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    Py_buffer buf;
    if (PyObject_GetBuffer(out, &buf, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        Py_DECREF(fast);
        return NULL;
    }
    if (buf.len < n_pad * (Py_ssize_t)sizeof(int64_t) ||
        buf.len < n * (Py_ssize_t)sizeof(int64_t)) {
        PyErr_Format(PyExc_ValueError,
                     "ts buffer too small: %zd bytes for %zd entries",
                     buf.len, (n_pad > n) ? n_pad : n);
        PyBuffer_Release(&buf);
        Py_DECREF(fast);
        return NULL;
    }
    int64_t *data = (int64_t *)buf.buf;
    int64_t last = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        long long x = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fast, i));
        if (x == -1 && PyErr_Occurred()) {
            PyBuffer_Release(&buf);
            Py_DECREF(fast);
            return NULL;
        }
        data[i] = (int64_t)x;
        last = (int64_t)x;
    }
    for (Py_ssize_t i = n; i < n_pad; i++)
        data[i] = last; /* monotone pad keeps searchsorted correct */
    PyBuffer_Release(&buf);
    Py_DECREF(fast);
    return Py_NewRef(Py_None);
}

/* --- pointer-identity intern memo -------------------------------------
 *
 * Producers that pool their string objects (a symbol universe, a parsed
 * dictionary — the common shape for market-data/telemetry feeds) send the
 * SAME PyObject* for a value over and over. A bounded open-addressing map
 * keyed on object identity turns the per-value PyDict_GetItem (hash every
 * character) into a pointer compare (~4 ns). Entries hold STRONG refs, so
 * a pointer can never be recycled for a different string while memoized;
 * only PERMANENT codes are memoized (append-only, never reassigned) —
 * transient uuid-ring codes recycle and must not be cached. The memo is
 * dropped wholesale on StringTable.restore (codes reassigned there). */

#define IDMEMO_BITS 13
#define IDMEMO_SIZE (1 << IDMEMO_BITS)  /* 8192 slots/attr, ~96 KB */

typedef struct {
    PyObject *keys[IDMEMO_SIZE]; /* strong refs or NULL */
    int32_t codes[IDMEMO_SIZE];
} id_memo;

static void
idmemo_capsule_destruct(PyObject *capsule)
{
    id_memo *m = (id_memo *)PyCapsule_GetPointer(capsule, "siddhi.idmemo");
    if (m == NULL)
        return;
    for (Py_ssize_t i = 0; i < IDMEMO_SIZE; i++)
        Py_XDECREF(m->keys[i]);
    PyMem_Free(m);
}

/* idmemo_new() -> capsule */
static PyObject *
idmemo_new(PyObject *self, PyObject *args)
{
    id_memo *m = PyMem_Calloc(1, sizeof(id_memo));
    if (m == NULL)
        return PyErr_NoMemory();
    return PyCapsule_New(m, "siddhi.idmemo", idmemo_capsule_destruct);
}

static inline size_t
idmemo_slot(PyObject *p)
{
    /* low bits of the pointer are alignment zeros; Fibonacci-mix the rest */
    return (size_t)(((uintptr_t)p >> 4) * (uintptr_t)0x9E3779B97F4A7C15ULL
                    >> (64 - IDMEMO_BITS));
}

/* intern_column(values, out: int32 buffer, to_code: dict, to_str: list,
 *               transient: dict[, memo_capsule]) — vectorized string
 * interning for one column (send_columns path); `transient` keeps live
 * uuid codes stable. */
static PyObject *
intern_column(PyObject *self, PyObject *args)
{
    PyObject *values, *out, *to_code, *to_str, *transient;
    PyObject *memo_capsule = NULL;
    if (!PyArg_ParseTuple(args, "OOO!O!O!|O", &values, &out,
                          &PyDict_Type, &to_code, &PyList_Type, &to_str,
                          &PyDict_Type, &transient, &memo_capsule))
        return NULL;
    id_memo *memo = NULL;
    if (memo_capsule != NULL && memo_capsule != Py_None) {
        memo = (id_memo *)PyCapsule_GetPointer(memo_capsule,
                                               "siddhi.idmemo");
        if (memo == NULL)
            return NULL;
    }
    PyObject *fast = PySequence_Fast(values, "values must be a sequence");
    if (fast == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    Py_buffer buf;
    if (PyObject_GetBuffer(out, &buf, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        Py_DECREF(fast);
        return NULL;
    }
    if (buf.len < n * (Py_ssize_t)sizeof(int32_t)) {
        PyErr_SetString(PyExc_ValueError, "intern_column: out buffer too small");
        PyBuffer_Release(&buf);
        Py_DECREF(fast);
        return NULL;
    }
    int32_t *data = (int32_t *)buf.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *v = PySequence_Fast_GET_ITEM(fast, i);
        size_t slot = 0, slot2 = 0;
        if (memo != NULL && v != Py_None) {
            slot = idmemo_slot(v);
            if (memo->keys[slot] == v) {
                data[i] = memo->codes[slot];
                continue;
            }
            slot2 = (slot + 1) & (IDMEMO_SIZE - 1); /* one probe step */
            if (memo->keys[slot2] == v) {
                data[i] = memo->codes[slot2];
                continue;
            }
        }
        int32_t code = intern_string(v, to_code, to_str, transient);
        if (code < 0 && PyErr_Occurred()) {
            PyBuffer_Release(&buf);
            Py_DECREF(fast);
            return NULL;
        }
        data[i] = code;
        /* memoize permanent codes only (transient ring codes recycle);
         * prefer an empty slot, else evict the probe slot */
        if (memo != NULL && v != Py_None && code < (1 << 30)) {
            size_t s = (memo->keys[slot] == NULL) ? slot : slot2;
            Py_XDECREF(memo->keys[s]);
            Py_INCREF(v);
            memo->keys[s] = v;
            memo->codes[s] = code;
        }
    }
    PyBuffer_Release(&buf);
    Py_DECREF(fast);
    return Py_NewRef(Py_None);
}

/* radix_argsort(keys: int32 C-contiguous buffer, out: int32 buffer)
 *
 * Stable LSD radix argsort over NON-NEGATIVE int32 keys (group slots,
 * emission ranks). XLA CPU lowers a stable argsort to a comparator sort
 * (~260 ns/elem measured at 282k lanes — 74 ms); numpy's "stable" for
 * int32 is mergesort-class (~28 ms). This 11-bit/pass LSD radix runs the
 * same width in ~2-3 ms and is called from inside jitted steps via
 * jax.pure_callback on the CPU backend only (TPU keeps lax.sort). */
static PyObject *
radix_argsort(PyObject *self, PyObject *args)
{
    PyObject *keys_obj, *out_obj;
    if (!PyArg_ParseTuple(args, "OO", &keys_obj, &out_obj))
        return NULL;
    Py_buffer kb, ob;
    if (PyObject_GetBuffer(keys_obj, &kb, PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(out_obj, &ob,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&kb);
        return NULL;
    }
    Py_ssize_t n = kb.len / (Py_ssize_t)sizeof(int32_t);
    if (ob.len < n * (Py_ssize_t)sizeof(int32_t)) {
        PyErr_SetString(PyExc_ValueError, "radix_argsort: out too small");
        PyBuffer_Release(&kb); PyBuffer_Release(&ob);
        return NULL;
    }
    const int32_t *keys = (const int32_t *)kb.buf;
    int32_t *out = (int32_t *)ob.buf;
    int32_t *tmp = PyMem_Malloc((size_t)n * sizeof(int32_t));
    if (tmp == NULL && n > 0) {
        PyBuffer_Release(&kb); PyBuffer_Release(&ob);
        return PyErr_NoMemory();
    }
    uint32_t maxk = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        uint32_t k = (uint32_t)keys[i];
        if (k > maxk) maxk = k;
    }
    for (Py_ssize_t i = 0; i < n; i++)
        out[i] = (int32_t)i;
#define RADIX_BITS 11
#define RADIX_SIZE (1 << RADIX_BITS)
    static _Thread_local uint32_t hist[RADIX_SIZE];
    int32_t *src = out, *dst = tmp;
    for (int shift = 0;
         shift == 0 || (shift < 32 && (maxk >> shift) != 0);
         shift += RADIX_BITS) {
        memset(hist, 0, sizeof(hist));
        for (Py_ssize_t i = 0; i < n; i++)
            hist[((uint32_t)keys[i] >> shift) & (RADIX_SIZE - 1)]++;
        uint32_t sum = 0;
        for (int b = 0; b < RADIX_SIZE; b++) {
            uint32_t c = hist[b];
            hist[b] = sum;
            sum += c;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t idx = src[i];
            uint32_t b = ((uint32_t)keys[idx] >> shift) & (RADIX_SIZE - 1);
            dst[hist[b]++] = idx;
        }
        int32_t *t = src; src = dst; dst = t;
    }
    if (src != out)
        memcpy(out, src, (size_t)n * sizeof(int32_t));
    PyMem_Free(tmp);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&ob);
    return Py_NewRef(Py_None);
}

/* map_codes(codes: int32 buffer, to_str: list) -> list[str|None]
 * — vectorized string-column decode; out-of-range codes map to None (the
 *   caller pre-screens transient codes and takes the Python path). */
static PyObject *
map_codes(PyObject *self, PyObject *args)
{
    PyObject *codes, *to_str;
    if (!PyArg_ParseTuple(args, "OO!", &codes, &PyList_Type, &to_str))
        return NULL;
    Py_buffer buf;
    if (PyObject_GetBuffer(codes, &buf, PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    Py_ssize_t n = buf.len / (Py_ssize_t)sizeof(int32_t);
    Py_ssize_t table_n = PyList_GET_SIZE(to_str);
    const int32_t *data = (const int32_t *)buf.buf;
    PyObject *result = PyList_New(n);
    if (result == NULL) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t c = data[i];
        PyObject *v = (c >= 0 && c < table_n) ? PyList_GET_ITEM(to_str, c)
                                              : Py_None;
        PyList_SET_ITEM(result, i, Py_NewRef(v));
    }
    PyBuffer_Release(&buf);
    return result;
}

/* build_events(event_cls, ts: int64 buffer, expired: uint8 buffer,
 *              cols: tuple[list]) -> list[Event]
 *
 * Decode hot loop: allocates Event instances via tp_alloc and fills the
 * three fields through their (pre-fetched) slot descriptors — bypassing
 * __init__ cuts per-event cost ~5x, which is the difference between the
 * public callback path keeping up with the device and not. */
static PyObject *
build_events(PyObject *self, PyObject *args)
{
    PyObject *cls_obj, *ts_obj, *exp_obj, *cols;
    if (!PyArg_ParseTuple(args, "OOOO!", &cls_obj, &ts_obj, &exp_obj,
                          &PyTuple_Type, &cols))
        return NULL;
    if (!PyType_Check(cls_obj)) {
        PyErr_SetString(PyExc_TypeError, "event_cls must be a type");
        return NULL;
    }
    PyTypeObject *cls = (PyTypeObject *)cls_obj;
    Py_ssize_t n_cols = PyTuple_GET_SIZE(cols);

    Py_buffer ts_buf, exp_buf;
    if (PyObject_GetBuffer(ts_obj, &ts_buf, PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(exp_obj, &exp_buf, PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&ts_buf);
        return NULL;
    }
    Py_ssize_t n = ts_buf.len / (Py_ssize_t)sizeof(int64_t);
    PyObject *result = NULL, *d_ts = NULL, *d_data = NULL, *d_exp = NULL;
    if (exp_buf.len < n) {
        PyErr_SetString(PyExc_ValueError, "expired buffer shorter than ts");
        goto fail;
    }
    for (Py_ssize_t c = 0; c < n_cols; c++) {
        PyObject *col = PyTuple_GET_ITEM(cols, c);
        if (!PyList_Check(col) || PyList_GET_SIZE(col) < n) {
            PyErr_Format(PyExc_ValueError,
                         "cols[%zd] must be a list of >= %zd items", c, n);
            goto fail;
        }
    }
    d_ts = PyObject_GetAttrString(cls_obj, "timestamp");
    d_data = PyObject_GetAttrString(cls_obj, "data");
    d_exp = PyObject_GetAttrString(cls_obj, "is_expired");
    if (!d_ts || !d_data || !d_exp)
        goto fail;
    descrsetfunc set_ts = Py_TYPE(d_ts)->tp_descr_set;
    descrsetfunc set_data = Py_TYPE(d_data)->tp_descr_set;
    descrsetfunc set_exp = Py_TYPE(d_exp)->tp_descr_set;
    if (!set_ts || !set_data || !set_exp) {
        PyErr_SetString(PyExc_TypeError,
                        "event_cls fields must be slot descriptors");
        goto fail;
    }
    const int64_t *ts_data = (const int64_t *)ts_buf.buf;
    const uint8_t *exp_data = (const uint8_t *)exp_buf.buf;

    result = PyList_New(n);
    if (result == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *data = PyTuple_New(n_cols);
        if (data == NULL)
            goto fail_clear;
        for (Py_ssize_t c = 0; c < n_cols; c++) {
            PyObject *v = PyList_GET_ITEM(PyTuple_GET_ITEM(cols, c), i);
            PyTuple_SET_ITEM(data, c, Py_NewRef(v));
        }
        PyObject *ev = cls->tp_alloc(cls, 0);
        if (ev == NULL) {
            Py_DECREF(data);
            goto fail_clear;
        }
        PyObject *ts_val = PyLong_FromLongLong((long long)ts_data[i]);
        if (ts_val == NULL ||
            set_ts(d_ts, ev, ts_val) < 0 ||
            set_data(d_data, ev, data) < 0 ||
            set_exp(d_exp, ev, exp_data[i] ? Py_True : Py_False) < 0) {
            Py_XDECREF(ts_val);
            Py_DECREF(data);
            Py_DECREF(ev);
            goto fail_clear;
        }
        Py_DECREF(ts_val);
        Py_DECREF(data); /* slot holds its own reference */
        /* untrack from the cyclic GC: events hold only a tuple of scalars /
         * strings (no cycles possible), and tracking millions of short-lived
         * objects makes gen-0 collections the decode bottleneck */
        if (PyObject_GC_IsTracked(data))
            PyObject_GC_UnTrack(data);
        if (PyObject_GC_IsTracked(ev))
            PyObject_GC_UnTrack(ev);
        PyList_SET_ITEM(result, i, ev);
    }
    goto done;

fail_clear:
    Py_CLEAR(result);
fail:
done:
    Py_XDECREF(d_ts);
    Py_XDECREF(d_data);
    Py_XDECREF(d_exp);
    PyBuffer_Release(&ts_buf);
    PyBuffer_Release(&exp_buf);
    return result;
}

/* ------------------------------------------------------------------------
 * MPSC staging ring — the Disruptor's role (reference:
 * core/stream/StreamJunction.java:279-316 ring buffer + worker consumers).
 *
 * Producers (source threads / user send) claim slots with a C11 atomic
 * fetch-add and publish with a per-slot sequence stamp; one consumer (the
 * junction's feeder thread) drains batches. Correct for true concurrent
 * producers (the design does not lean on the GIL for the index protocol;
 * the PyObject* payloads themselves are only touched under the GIL, which
 * every Python-level producer and the feeder hold at the call boundary).
 * ---------------------------------------------------------------------- */

#include <stdatomic.h>

#include "colring_core.h"

typedef struct {
    Py_ssize_t cap;
    atomic_size_t head;       /* next slot to claim (producers) */
    size_t tail;              /* next slot to read (single consumer) */
    atomic_size_t *seq;       /* published when seq[i % cap] == i + 1 */
    PyObject **rows;          /* owned references */
    int64_t *ts;
} mpsc_ring;

static void
ring_capsule_destruct(PyObject *capsule)
{
    mpsc_ring *r = (mpsc_ring *)PyCapsule_GetPointer(capsule, "siddhi.ring");
    if (r == NULL)
        return;
    for (size_t i = r->tail; i < atomic_load(&r->head); i++) {
        size_t s = i % (size_t)r->cap;
        if (atomic_load(&r->seq[s]) == i + 1)
            Py_XDECREF(r->rows[s]);
    }
    PyMem_Free(r->seq);
    PyMem_Free(r->rows);
    PyMem_Free(r->ts);
    PyMem_Free(r);
}

/* ring_new(capacity) -> capsule */
static PyObject *
ring_new(PyObject *self, PyObject *args)
{
    Py_ssize_t cap;
    if (!PyArg_ParseTuple(args, "n", &cap))
        return NULL;
    if (cap < 1) {
        PyErr_SetString(PyExc_ValueError, "ring capacity must be >= 1");
        return NULL;
    }
    mpsc_ring *r = PyMem_Calloc(1, sizeof(mpsc_ring));
    if (r == NULL)
        return PyErr_NoMemory();
    r->cap = cap;
    atomic_init(&r->head, 0);
    r->tail = 0;
    r->seq = PyMem_Calloc((size_t)cap, sizeof(atomic_size_t));
    r->rows = PyMem_Calloc((size_t)cap, sizeof(PyObject *));
    r->ts = PyMem_Calloc((size_t)cap, sizeof(int64_t));
    if (!r->seq || !r->rows || !r->ts) {
        PyMem_Free(r->seq); PyMem_Free(r->rows); PyMem_Free(r->ts);
        PyMem_Free(r);
        return PyErr_NoMemory();
    }
    return PyCapsule_New(r, "siddhi.ring", ring_capsule_destruct);
}

static mpsc_ring *
ring_of(PyObject *capsule)
{
    return (mpsc_ring *)PyCapsule_GetPointer(capsule, "siddhi.ring");
}

/* ring_push(ring, ts, row) -> bool (False = full, caller applies
 * backpressure like the Disruptor's blocking wait) */
static PyObject *
ring_push(PyObject *self, PyObject *args)
{
    PyObject *capsule, *row;
    long long ts;
    if (!PyArg_ParseTuple(args, "OLO", &capsule, &ts, &row))
        return NULL;
    mpsc_ring *r = ring_of(capsule);
    if (r == NULL)
        return NULL;
    size_t cap = (size_t)r->cap;
    size_t claimed = atomic_load(&r->head);
    for (;;) {
        if (claimed - r->tail >= cap)
            Py_RETURN_FALSE; /* full */
        if (atomic_compare_exchange_weak(&r->head, &claimed, claimed + 1))
            break;
    }
    size_t s = claimed % cap;
    Py_INCREF(row);
    r->rows[s] = row;
    r->ts[s] = (int64_t)ts;
    atomic_store(&r->seq[s], claimed + 1); /* publish */
    Py_RETURN_TRUE;
}

/* ring_pop_batch(ring, max_n) -> (ts_list, row_list) — single consumer */
static PyObject *
ring_pop_batch(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    Py_ssize_t max_n;
    if (!PyArg_ParseTuple(args, "On", &capsule, &max_n))
        return NULL;
    mpsc_ring *r = ring_of(capsule);
    if (r == NULL)
        return NULL;
    PyObject *ts_list = PyList_New(0);
    PyObject *row_list = PyList_New(0);
    if (!ts_list || !row_list) {
        Py_XDECREF(ts_list);
        Py_XDECREF(row_list);
        return NULL;
    }
    size_t cap = (size_t)r->cap;
    for (Py_ssize_t n = 0; n < max_n; n++) {
        size_t i = r->tail;
        size_t s = i % cap;
        if (atomic_load(&r->seq[s]) != i + 1)
            break; /* not yet published (or empty) */
        PyObject *ts_obj = PyLong_FromLongLong((long long)r->ts[s]);
        if (ts_obj == NULL || PyList_Append(ts_list, ts_obj) < 0 ||
            PyList_Append(row_list, r->rows[s]) < 0) {
            Py_XDECREF(ts_obj);
            Py_DECREF(ts_list);
            Py_DECREF(row_list);
            return NULL;
        }
        Py_DECREF(ts_obj);
        Py_DECREF(r->rows[s]);
        r->rows[s] = NULL;
        atomic_store(&r->seq[s], 0);
        r->tail = i + 1;
    }
    return Py_BuildValue("(NN)", ts_list, row_list);
}

/* ring_size(ring) -> int (published, unconsumed entries; approximate
 * under concurrent producers) */
static PyObject *
ring_size(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    mpsc_ring *r = ring_of(capsule);
    if (r == NULL)
        return NULL;
    return PyLong_FromSize_t(atomic_load(&r->head) - r->tail);
}

/* ------------------------------------------------------------------------
 * Lock-free multi-producer COLUMNAR ring — the zero-copy ingress stage.
 *
 * Where the MPSC ring above stages PyObject* rows (decoded under the GIL by
 * the feeder), this ring stages raw columnar bytes: fixed-width native
 * buffers, one per attribute (string attrs as pre-interned int32 dictionary
 * codes). Producers claim a contiguous run of slots with one CAS
 * (claim-then-write, Disruptor-style, so parallel encode workers can fill
 * their runs out of order while consumption stays in claim order), write
 * with the GIL RELEASED (the payload is plain memory — memcpy needs no
 * interpreter), and publish per-slot sequence stamps. One consumer copies
 * contiguous published runs out into caller buffers, also without the GIL.
 *
 * The claim/publish/consume protocol itself lives in colring_core.h (pure
 * C11, no Python.h) so native/colring_stress.c can compile the IDENTICAL
 * code under TSan/ASan/UBSan; these wrappers own arg parsing, Py_buffer
 * handling, payload memcpy, and the GIL.
 * ---------------------------------------------------------------------- */

#define COLRING_MAX_COLS 64

typedef struct {
    crc_ring rc;              /* claim/publish protocol (colring_core.h) */
    int n_cols;
    Py_ssize_t widths[COLRING_MAX_COLS];
    char *cols[COLRING_MAX_COLS];   /* cap * width bytes each */
    int64_t *ts;
} colring;

static void
colring_capsule_destruct(PyObject *capsule)
{
    colring *r = (colring *)PyCapsule_GetPointer(capsule, "siddhi.colring");
    if (r == NULL)
        return;
    for (int c = 0; c < r->n_cols; c++)
        PyMem_Free(r->cols[c]);
    PyMem_Free(r->ts);
    PyMem_Free(r->rc.seq);
    PyMem_Free(r);
}

static Py_ssize_t
colring_width(char tc)
{
    switch (tc) {
    case 'b': return 1;
    case 'i': return 4;
    case 'l': return 8;
    case 'f': return 4;
    case 'd': return 8;
    case 's': return 4;  /* pre-interned int32 dictionary codes */
    default:  return 0;
    }
}

/* colring_new(capacity, typecodes: bytes) -> capsule */
static PyObject *
colring_new(PyObject *self, PyObject *args)
{
    Py_ssize_t cap_req;
    PyObject *typecodes_obj;
    if (!PyArg_ParseTuple(args, "nS", &cap_req, &typecodes_obj))
        return NULL;
    if (cap_req < 1) {
        PyErr_SetString(PyExc_ValueError, "colring capacity must be >= 1");
        return NULL;
    }
    Py_ssize_t n_cols = PyBytes_GET_SIZE(typecodes_obj);
    if (n_cols > COLRING_MAX_COLS) {
        PyErr_Format(PyExc_ValueError, "colring supports at most %d columns",
                     COLRING_MAX_COLS);
        return NULL;
    }
    size_t cap = 1;
    while (cap < (size_t)cap_req)
        cap <<= 1;
    colring *r = PyMem_Calloc(1, sizeof(colring));
    if (r == NULL)
        return PyErr_NoMemory();
    r->n_cols = (int)n_cols;
    const char *tcs = PyBytes_AS_STRING(typecodes_obj);
    r->ts = PyMem_Malloc(cap * sizeof(int64_t));
    crc_init(&r->rc, PyMem_Calloc(cap, sizeof(crc_seq)), cap);
    if (r->ts == NULL || r->rc.seq == NULL) {
        PyErr_NoMemory();
        goto fail;
    }
    for (Py_ssize_t c = 0; c < n_cols; c++) {
        Py_ssize_t w = colring_width(tcs[c]);
        if (w == 0) {
            PyErr_Format(PyExc_ValueError, "bad type code %c", tcs[c]);
            goto fail;
        }
        r->widths[c] = w;
        r->cols[c] = PyMem_Malloc(cap * (size_t)w);
        if (r->cols[c] == NULL) {
            PyErr_NoMemory();
            goto fail;
        }
    }
    return PyCapsule_New(r, "siddhi.colring", colring_capsule_destruct);

fail:
    for (Py_ssize_t k = 0; k < n_cols; k++)
        PyMem_Free(r->cols[k]);  /* calloc'd struct: unset slots are NULL */
    PyMem_Free(r->ts);
    PyMem_Free(r->rc.seq);
    PyMem_Free(r);
    return NULL;
}

static colring *
colring_of(PyObject *capsule)
{
    return (colring *)PyCapsule_GetPointer(capsule, "siddhi.colring");
}

/* colring_claim(ring, n) -> start index, or -1 when the ring lacks n free
 * slots (all-or-nothing; the caller spins/backpressures). One CAS claims
 * the whole contiguous run — claim order IS delivery order, which is what
 * makes parallel out-of-order encode workers deterministic downstream. */
static PyObject *
colring_claim(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "On", &capsule, &n))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    if (n < 1 || (size_t)n > r->rc.cap) {
        PyErr_Format(PyExc_ValueError,
                     "colring_claim: n=%zd out of range (cap %zu)",
                     n, r->rc.cap);
        return NULL;
    }
    ptrdiff_t start = crc_claim(&r->rc, (size_t)n);
    if (start < 0)
        return PyLong_FromLong(-1); /* insufficient free space */
    return PyLong_FromUnsignedLongLong((unsigned long long)start);
}

/* colring_write(ring, start, n, ts_buf: int64[n], cols: tuple[buffer]) —
 * copy one claimed run into the ring and publish it. The copies run with
 * the GIL released; string columns arrive here already interned to int32
 * codes (interning is the only stage that still batch-acquires the GIL,
 * in the worker pool above this). */
static PyObject *
colring_write(PyObject *self, PyObject *args)
{
    PyObject *capsule, *ts_obj, *cols;
    unsigned long long start;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "OKnOO!", &capsule, &start, &n, &ts_obj,
                          &PyTuple_Type, &cols))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    if (PyTuple_GET_SIZE(cols) != r->n_cols) {
        PyErr_Format(PyExc_ValueError, "colring_write: expected %d columns",
                     r->n_cols);
        return NULL;
    }
    Py_buffer ts_buf;
    Py_buffer bufs[COLRING_MAX_COLS];
    if (PyObject_GetBuffer(ts_obj, &ts_buf, PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (ts_buf.len < n * (Py_ssize_t)sizeof(int64_t)) {
        PyErr_SetString(PyExc_ValueError, "colring_write: ts buffer short");
        PyBuffer_Release(&ts_buf);
        return NULL;
    }
    int acquired = 0;
    for (; acquired < r->n_cols; acquired++) {
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(cols, acquired),
                               &bufs[acquired], PyBUF_C_CONTIGUOUS) < 0)
            goto fail;
        if (bufs[acquired].len < n * r->widths[acquired]) {
            PyErr_Format(PyExc_ValueError,
                         "colring_write: column %d buffer short", acquired);
            acquired++;
            goto fail;
        }
    }
    Py_BEGIN_ALLOW_THREADS
    {
        size_t s0 = (size_t)start & r->rc.mask;
        size_t first = r->rc.cap - s0;       /* slots before wrap */
        if (first > (size_t)n)
            first = (size_t)n;
        size_t second = (size_t)n - first;
        memcpy(r->ts + s0, ts_buf.buf, first * sizeof(int64_t));
        if (second)
            memcpy(r->ts, (const int64_t *)ts_buf.buf + first,
                   second * sizeof(int64_t));
        for (int c = 0; c < r->n_cols; c++) {
            size_t w = (size_t)r->widths[c];
            const char *src = (const char *)bufs[c].buf;
            memcpy(r->cols[c] + s0 * w, src, first * w);
            if (second)
                memcpy(r->cols[c], src + first * w, second * w);
        }
        /* publish AFTER the data: crc_publish's release stores pair with
         * the consumer's acquire loads, slot by slot */
        crc_publish(&r->rc, (size_t)start, (size_t)n);
    }
    Py_END_ALLOW_THREADS
    for (int i = 0; i < acquired; i++)
        PyBuffer_Release(&bufs[i]);
    PyBuffer_Release(&ts_buf);
    Py_RETURN_NONE;

fail:
    for (int i = 0; i < acquired; i++)
        PyBuffer_Release(&bufs[i]);
    PyBuffer_Release(&ts_buf);
    return NULL;
}

/* colring_pop(ring, max_n, ts_out: int64 buffer, cols_out: tuple[buffer])
 * -> n copied (0 when nothing contiguous is published). Single consumer. */
static PyObject *
colring_pop(PyObject *self, PyObject *args)
{
    PyObject *capsule, *ts_obj, *cols;
    Py_ssize_t max_n;
    if (!PyArg_ParseTuple(args, "OnOO!", &capsule, &max_n, &ts_obj,
                          &PyTuple_Type, &cols))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    if (PyTuple_GET_SIZE(cols) != r->n_cols) {
        PyErr_Format(PyExc_ValueError, "colring_pop: expected %d columns",
                     r->n_cols);
        return NULL;
    }
    Py_buffer ts_buf;
    Py_buffer bufs[COLRING_MAX_COLS];
    if (PyObject_GetBuffer(ts_obj, &ts_buf,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    int acquired = 0;
    for (; acquired < r->n_cols; acquired++) {
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(cols, acquired),
                               &bufs[acquired],
                               PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
            goto fail;
    }
    /* bound max_n by the output buffers up front */
    if (ts_buf.len / (Py_ssize_t)sizeof(int64_t) < max_n)
        max_n = ts_buf.len / (Py_ssize_t)sizeof(int64_t);
    for (int c = 0; c < r->n_cols; c++)
        if (bufs[c].len / r->widths[c] < max_n)
            max_n = bufs[c].len / r->widths[c];
    if (max_n < 0)
        max_n = 0;
    size_t n = crc_poll(&r->rc, (size_t)max_n);
    if (n > 0) {
        Py_BEGIN_ALLOW_THREADS
        {
            size_t t = atomic_load_explicit(&r->rc.tail,
                                            memory_order_relaxed);
            size_t s0 = t & r->rc.mask;
            size_t first = r->rc.cap - s0;
            if (first > n)
                first = n;
            size_t second = n - first;
            memcpy(ts_buf.buf, r->ts + s0, first * sizeof(int64_t));
            if (second)
                memcpy((int64_t *)ts_buf.buf + first, r->ts,
                       second * sizeof(int64_t));
            for (int c = 0; c < r->n_cols; c++) {
                size_t w = (size_t)r->widths[c];
                char *dst = (char *)bufs[c].buf;
                memcpy(dst, r->cols[c] + s0 * w, first * w);
                if (second)
                    memcpy(dst + first * w, r->cols[c], second * w);
            }
            crc_consume(&r->rc, n);
        }
        Py_END_ALLOW_THREADS
    }
    for (int i = 0; i < acquired; i++)
        PyBuffer_Release(&bufs[i]);
    PyBuffer_Release(&ts_buf);
    return PyLong_FromSize_t(n);

fail:
    for (int i = 0; i < acquired; i++)
        PyBuffer_Release(&bufs[i]);
    PyBuffer_Release(&ts_buf);
    return NULL;
}

/* colring_size(ring) -> claimed, unconsumed depth (approximate under
 * concurrent producers; includes claimed-but-unwritten runs) */
static PyObject *
colring_size(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    return PyLong_FromSize_t(crc_size(&r->rc));
}

/* colring_capacity(ring) -> rounded power-of-two slot count */
static PyObject *
colring_capacity(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    return PyLong_FromSize_t(r->rc.cap);
}

/* colring_hwm(ring) -> claimed-depth high-water mark over the ring's life */
static PyObject *
colring_hwm(PyObject *self, PyObject *args)
{
    PyObject *capsule;
    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    colring *r = colring_of(capsule);
    if (r == NULL)
        return NULL;
    return PyLong_FromSize_t(crc_hwm(&r->rc));
}

static PyMethodDef methods[] = {
    {"encode_rows", encode_rows, METH_VARARGS,
     "Encode row tuples into columnar buffers with string interning."},
    {"fill_ts", fill_ts, METH_VARARGS,
     "Fill an int64 timestamp buffer with monotone padding."},
    {"idmemo_new", idmemo_new, METH_VARARGS,
     "idmemo_new() -> capsule: pointer-identity intern memo"},
    {"intern_column", intern_column, METH_VARARGS,
     "Intern a string column into an int32 code buffer."},
    {"radix_argsort", radix_argsort, METH_VARARGS,
     "radix_argsort(keys_i32, out_i32): stable LSD radix argsort"},
    {"map_codes", map_codes, METH_VARARGS,
     "Decode an int32 code buffer through a string table list."},
    {"build_events", build_events, METH_VARARGS,
     "Construct a list of Event objects from decoded columns."},
    {"ring_new", ring_new, METH_VARARGS,
     "Create an MPSC staging ring of (ts, row) slots."},
    {"ring_push", ring_push, METH_VARARGS,
     "Push one (ts, row); returns False when full (backpressure)."},
    {"ring_pop_batch", ring_pop_batch, METH_VARARGS,
     "Drain up to max_n published entries (single consumer)."},
    {"ring_size", ring_size, METH_VARARGS,
     "Published, unconsumed entry count."},
    {"colring_new", colring_new, METH_VARARGS,
     "Create a lock-free multi-producer columnar ring (capacity, typecodes)."},
    {"colring_claim", colring_claim, METH_VARARGS,
     "CAS-claim n contiguous slots; returns start index or -1 when full."},
    {"colring_write", colring_write, METH_VARARGS,
     "Copy a claimed run's ts+columns into the ring and publish (GIL released)."},
    {"colring_pop", colring_pop, METH_VARARGS,
     "Copy the contiguous published prefix out (single consumer, GIL released)."},
    {"colring_size", colring_size, METH_VARARGS,
     "Claimed, unconsumed slot count."},
    {"colring_capacity", colring_capacity, METH_VARARGS,
     "Rounded power-of-two slot capacity."},
    {"colring_hwm", colring_hwm, METH_VARARGS,
     "Claimed-depth high-water mark."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_siddhi_native",
    "Native host-path marshalling for siddhi_tpu.", -1, methods,
};

PyMODINIT_FUNC
PyInit__siddhi_native(void)
{
    return PyModule_Create(&module);
}
