"""Build the native host-path extension:

    python native/setup.py build_ext --build-lib <dir>

siddhi_tpu.native builds this lazily on first import (cached under
siddhi_tpu/_native_build/) and falls back to the pure-Python encoder when no
compiler is available."""

from setuptools import Extension, setup

setup(
    name="siddhi-tpu-native",
    ext_modules=[
        Extension(
            "_siddhi_native",
            sources=["columnar.c"],
            extra_compile_args=["-O3"],
        )
    ],
)
