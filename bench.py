"""Driver benchmark — prints ONE JSON line.

Config: BASELINE.md #2 — lengthBatch(10000) window, sum/avg group-by over 1M
distinct keys (the north-star sliding-window group-by shape). Events are
synthesized host-side as pre-encoded columnar batches (dictionary interning is
amortized in steady state) and pushed through the jitted query step on the
default device (real TPU under the driver; CPU elsewhere).

vs_baseline: BASELINE.json `published` is empty and no JVM exists in this image
to measure the reference, so the denominator defaults to a nominal 1.0M
events/sec single-JVM CPU figure (WSO2's published order-of-magnitude for
simple Siddhi queries; documented assumption). If a measured number is added to
BASELINE.json under published["groupby_window_events_per_sec"], it is used
instead.
"""

from __future__ import annotations

import json
import time

import numpy as np

BATCH = 8192
N_KEYS = 1_000_000
WINDOW = 10_000
WARMUP = 3
STEPS = 40

APP = f"""
define stream TradeStream (symbol string, price double, volume long);
@info(name = 'bench')
from TradeStream#window.lengthBatch({WINDOW})
select symbol, sum(price) as total, avg(price) as avgPrice
group by symbol
insert into SummaryStream;
"""


def main() -> None:
    import jax
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.event import EventBatch

    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        APP, batch_size=BATCH, group_capacity=1 << 20)
    qr = rt.query_runtimes["bench"]

    rng = np.random.default_rng(7)
    n_distinct_batches = 8  # cycle through pre-built batches
    batches = []
    ts0 = 1
    for i in range(n_distinct_batches):
        ts = np.arange(ts0, ts0 + BATCH, dtype=np.int64)
        ts0 += BATCH
        cols = {
            # pre-encoded dictionary codes (1..N_KEYS); code 0 is null
            "symbol": rng.integers(1, N_KEYS + 1, BATCH, dtype=np.int32),
            "price": rng.uniform(1.0, 100.0, BATCH).astype(np.float32),
            "volume": rng.integers(1, 1000, BATCH, dtype=np.int64),
        }
        batches.append(EventBatch.from_numpy(ts, cols, BATCH))

    state = qr.state
    step = qr._step

    # warmup / compile
    for i in range(WARMUP):
        state, out = step(state, batches[i % n_distinct_batches], jnp.int64(ts0))
    jax.block_until_ready(out)

    # throughput: pipelined (async dispatch, one barrier at the end) — the
    # steady-state streaming mode; batches stay in flight like the reference's
    # Disruptor pipeline. Through the axon tunnel a per-step block costs
    # ~80 ms of RPC sync alone, which would measure the tunnel, not the engine.
    # Best of 3 windows: the shared tunnel's throughput varies run-to-run.
    events_per_sec = 0.0
    for _rep in range(3):
        t_start = time.perf_counter()
        for i in range(STEPS):
            state, out = step(state, batches[i % n_distinct_batches],
                              jnp.int64(ts0))
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - t_start
        events_per_sec = max(events_per_sec, BATCH * STEPS / elapsed)

    # p99 batch latency: synchronous per-step round trips (includes host sync)
    lat = []
    for i in range(50):
        t0 = time.perf_counter()
        state, out = step(state, batches[i % n_distinct_batches], jnp.int64(ts0))
        jax.block_until_ready(out)
        lat.append(time.perf_counter() - t0)
    p99_ms = float(np.percentile(np.array(lat), 99) * 1e3)

    baseline = 1_000_000.0
    try:
        with open("BASELINE.json") as f:
            pub = json.load(f).get("published", {})
        baseline = float(pub.get("groupby_window_events_per_sec", baseline))
    except Exception:
        pass

    print(json.dumps({
        "metric": "lengthBatch10k_groupby_1M_keys_events_per_sec",
        "value": round(events_per_sec, 1),
        "unit": "events/sec",
        "vs_baseline": round(events_per_sec / baseline, 3),
        "p99_batch_latency_ms": round(p99_ms, 3),
    }))


if __name__ == "__main__":
    main()
