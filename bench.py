"""Driver benchmark — one JSON line per BASELINE.md config, headline last.

Configs (BASELINE.md "Baselines to measure"):
  1. filter      — single filter+project query (SimpleFilterSingleQueryPerformance shape)
  2. groupby     — lengthBatch(10000) sum/avg group-by over 1M keys  ◄ HEADLINE (printed last)
  3. distinct    — 60-sec sliding time window, exact distinctCount
  4. pattern     — every A -> B[b.val == a.val] within 5 sec (batched NFA)
  5. join        — stream-stream equi join over two length(100k) windows
  6. overload    — bounded-ingress drop.old under a 10x producer/consumer
                   mismatch: sustained delivery rate + exact drop counts
  7. upgrade     — blue-green hot-swap under sustained traffic: cutover
                   pause ms + exact conservation (sent == delivered)

Events are synthesized host-side as pre-encoded columnar batches (dictionary
interning amortizes in steady state) and pushed through each query's jitted
step on the default device (real TPU under the driver; CPU elsewhere).
Throughput is pipelined (async dispatch, one barrier per window, best of 3 —
through the axon tunnel a per-step block costs ~80 ms of RPC sync alone,
which would measure the tunnel, not the engine). p99 is synchronous per-step.

Each config's JSON line carries three numbers (VERDICT r02 item 8):
  value                 — pipelined throughput through the jitted step
                          (async dispatch, one barrier per window, best of 3)
  e2e_events_per_sec    — the PUBLIC path: InputHandler.send_columns(numpy
                          columns; string symbols as Python objects from a
                          pooled universe, interned per value by the native
                          encoder) → junction dispatch → jitted step →
                          async columnar callback (ColumnarBlock — the
                          batch-level form of the reference's Event[]
                          callback, StreamCallback.java:38). The clock
                          includes runtime.drain(): every output event has
                          reached the callback before the elapsed is read.
                          On the tunneled TPU each batch still pays the
                          device→host readback RTT (pipelined by the async
                          decoder); e2e_colocated_events_per_sec is the same
                          measurement with a co-located CPU backend in a
                          fresh subprocess — engine vs topology, separated.
  e2e_rows_events_per_sec — secondary: the same path fed with per-row
                          Python tuples (send_batch) and per-Event
                          callbacks — the row-at-a-time public API
  device_step_ms        — per-step time of the state-chained pipelined loop
                          (the chain serializes device execution, dispatch
                          overlaps: device-bound to first order), vs
  p99_batch_latency_ms  — synchronous single-step round trip, which on the
                          tunneled TPU includes the RPC sync cost.

vs_baseline: BASELINE.json `published` is empty and no JVM exists in this
image to measure the reference, so each denominator falls back to the
per-config estimates in `_DENOMINATORS` below — per-shape order-of-magnitude
figures for single-JVM CPU Siddhi, chosen HIGH (favoring the reference) so
ratios are conservative. Measured numbers added to BASELINE.json under
published[<metric key>] take precedence.

WATCHDOG DISCIPLINE (round 6 — BENCH_r05 produced ZERO numbers because the
first config hung >=900 s under the TPU driver): the bench can no longer go
dark. Every config runs in its own subprocess under a hard parent-side
deadline; the child emits `#partial {json}` checkpoints after each measured
sub-metric AND arms a best-effort SIGALRM, so when the parent kills a wedged
config it still merges the partials into a numeric JSON line tagged
"partial": true. A `--max-seconds` total budget bounds the whole run;
heartbeat progress lines go to stderr every 10 s. Steady-state numbers
exclude compilation: e2e runtimes start with AOT warmup
(SiddhiAppRuntime.warmup — the shape-bucket ladder compiles before the
clock starts).

Usage: python bench.py [config ...] [--max-seconds=N] [--config-seconds=N]
       (default: all five configs, headline last; N defaults 850 / 240)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

BATCH = 8192
#: e2e micro-batch: the public path amortizes per-batch costs (one device
#: dispatch + one device→host readback per batch) over more events; through
#: the tunneled TPU the readback RTT (~100 ms) is the dominant per-batch
#: cost, so e2e uses a larger compiled batch than the device measure.
#: BACKEND-AWARE (round 6): on a co-located CPU there is no tunnel to
#: amortize, and XLA's compile time for a 128k-lane aggregation step grows
#: into minutes on small hosts — CPU runs use 16384 so every config fits
#: its watchdog budget. SIDDHI_E2E_BATCH overrides either way; resolved
#: lazily in the child (after the backend is forced) via _resolve_e2e_batch.
E2E_BATCH = int(os.environ.get("SIDDHI_E2E_BATCH", 0)) or None


def _is_cpu() -> bool:
    # importing siddhi_tpu FIRST matters: its __init__ disables XLA:CPU
    # async dispatch (pure_callback deadlock guard), and the flag only
    # takes effect if set before jax creates its CPU client — which
    # jax.default_backend() does
    import siddhi_tpu  # noqa: F401
    import jax
    return jax.default_backend() == "cpu"


def _resolve_e2e_batch() -> int:
    global E2E_BATCH
    if E2E_BATCH is None:
        E2E_BATCH = 16384 if _is_cpu() else 131072
    return E2E_BATCH
WARMUP = 3
STEPS = 40
LAT_STEPS = 50
RNG_SEED = 7
#: --e2e-only: skip device measures, print only the e2e number (used by the
#: parent process to collect the co-located CPU variant)
E2E_ONLY = "--e2e-only" in sys.argv
T0 = time.monotonic()


def _flag(name: str, default: float) -> float:
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return float(a.split("=", 1)[1])
    return default


#: total wall budget for the whole run (parent mode) — chosen under the
#: driver's observed 900 s per-command ceiling
MAX_SECONDS = _flag("max-seconds", 850.0)
#: per-config watchdog: the parent kills a config subprocess at this bound
#: (clamped to the remaining total budget) and emits its partials
CONFIG_SECONDS = _flag("config-seconds", 240.0)

#: child-mode partial results: every measured sub-metric lands here AND is
#: echoed as a `#partial {json}` stdout line, so a killed child still
#: yields numbers for whatever finished
PARTIAL: dict = {}
_PHASE = ["init"]


def _phase(name: str) -> None:
    _PHASE[0] = name
    print(f"[bench] t={time.monotonic() - T0:.0f}s phase={name}",
          file=sys.stderr, flush=True)


def _partial(res: dict) -> None:
    PARTIAL.update(res)
    print("#partial " + json.dumps(res), flush=True)


class BenchTimeout(Exception):
    """Raised by the child's SIGALRM handler (best-effort in-process bound;
    the parent's kill is the hard one)."""


def _arm_child_watchdog(seconds: float) -> None:
    """SIGALRM -> BenchTimeout, plus a stderr heartbeat thread. The alarm
    fires only when the main thread executes Python bytecode — a hang
    inside one XLA compile outlives it, which is why the parent holds the
    authoritative deadline."""
    import signal
    if seconds > 0 and hasattr(signal, "SIGALRM"):
        def _on_alarm(_sig, _frm):
            raise BenchTimeout(f"alarm after {seconds:.0f}s")
        signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(max(int(seconds), 1))

    def _beat():
        while True:
            time.sleep(10)
            print(f"[bench] t={time.monotonic() - T0:.0f}s "
                  f"phase={_PHASE[0]} alive", file=sys.stderr, flush=True)

    threading.Thread(target=_beat, daemon=True, name="bench-heartbeat").start()


#: per-config single-JVM CPU estimates (events/sec), used when BASELINE.json
#: publishes no measured number. Basis: the reference's performance-samples
#: print throughput for these shapes on one JVM; per-event costs differ by
#: orders of magnitude across shapes (a filter is one virtual call per event;
#: a window join is a per-event find() against a 100k-event window). Chosen
#: at the HIGH end of plausible for the reference so vs_baseline understates
#: rather than flatters.
_DENOMINATORS = {
    # tight per-event filter loop, no state: millions/sec per core
    "filter_events_per_sec": 5_000_000.0,
    # per-event HashMap aggregation over 1M keys + 10k-batch flushes
    "lengthBatch10k_groupby_1M_keys_events_per_sec": 1_000_000.0,
    # sliding expiry walk + per-value distinct map per event
    "sliding60s_distinctCount_events_per_sec": 500_000.0,
    # per-event NFA pending-list scan with within-expiry
    "pattern_everyAB_within5s_events_per_sec": 500_000.0,
    # per-event find() against the opposite 100k-event window (the
    # reference has no window hash index; its per-event probe walks the
    # window's event chain with a compiled condition)
    "join_100kx100k_events_per_sec": 500_000.0,
    # sustained delivery under 10x overload with a bounded @async buffer:
    # bounded by the injected 2 ms/step consumer stall, not the engine —
    # denominator chosen as the reference's single-JVM ring throughput
    "overload_sustained_events_per_sec": 1_000_000.0,
    # multi-producer binary ingestion through the service surface into a
    # filter -> group-by app: the reference's HTTP/TCP source + Disruptor
    # ring tops out around its single-JVM ring throughput; the per-event
    # path is one mapper call + ring publish per event
    "e2e_ingress_events_per_sec": 1_000_000.0,
    # 256 co-resident queries: every event visits every query's per-event
    # callback chain in the reference, so single-JVM throughput divides by
    # query count; 100k favors the reference for this shape
    "fanout256_events_per_sec": 100_000.0,
    # partition-key sharded pipeline replicas behind the frame router: the
    # reference's comparable deployment is one JVM per partition group
    # behind an external partitioner, bounded by its single-JVM ring rate
    "sharded_e2e_events_per_sec": 1_000_000.0,
    # sustained rate under Poisson attach/detach churn: the reference
    # redeploys the whole app per membership change (stop-the-world), so
    # its sustained number under churn collapses toward redeploy time;
    # denominator matches the fanout shape it churns over
    "churn_sustained_events_per_sec": 100_000.0,
}


def _preflight(app: str) -> dict:
    """Static-analysis overhead per config app: parse, lint (the SL rule
    catalog over the plan graph), and full validate (plan + discard, the
    SIDDHI_LINT=error worst case). One-shot wall times in ms — these land
    in BENCH_*.json so lint cost regressions show up next to throughput."""
    from siddhi_tpu import SiddhiManager, compiler
    from siddhi_tpu.analysis import analyze

    t0 = time.perf_counter()
    parsed = compiler.parse(app)
    parse_ms = (time.perf_counter() - t0) * 1e3
    t1 = time.perf_counter()
    report = analyze(parsed)
    lint_ms = (time.perf_counter() - t1) * 1e3
    t2 = time.perf_counter()
    SiddhiManager().validate_siddhi_app(parsed)
    validate_ms = (time.perf_counter() - t2) * 1e3
    out = {
        "parse_ms": round(parse_ms, 2),
        "lint_ms": round(lint_ms, 2),
        "validate_ms": round(validate_ms, 2),
        "lint_findings": len(report.diagnostics),
    }
    if report.cost is not None:
        # advisory prediction (analysis/cost.py) riding next to the
        # measurement; bench_compare ignores these when diffing rounds
        out["cost_predicted_state_bytes"] = \
            report.cost["predicted_state_bytes"]
        out["cost_predicted_compiles"] = report.cost["predicted_compiles"]
    _partial(out)
    return out


def _baseline_for(key: str) -> float:
    fallback = _DENOMINATORS.get(key, 1_000_000.0)
    try:
        with open("BASELINE.json") as f:
            pub = json.load(f).get("published", {})
        return float(pub.get(key, fallback))
    except Exception:
        return fallback


def _measure(run_step, events_per_step: int, metric: str, *,
             warmup: int = WARMUP, steps: int = STEPS) -> dict:
    """run_step(i) -> device out; pipelined best-of-3 + synchronous p99.
    Warmup is BOUNDED: it stops early once it has burned half the child's
    remaining alarm budget (first-compile pathologies then surface as a
    `warmup_truncated` partial instead of a silent hang)."""
    import jax

    if _is_cpu():
        # CPU hosts pay 10-100x per device step: a quarter of the step
        # count still averages over enough steps to be stable, and keeps
        # each config inside its fair-share slice of the outer deadline
        steps = max(8, steps // 4)
    _phase(f"{metric}:warmup")
    w0 = time.monotonic()
    w_budget = max(CONFIG_SECONDS / 2, 30.0)
    done = 0
    out = None
    for i in range(warmup):
        out = run_step(i)
        jax.block_until_ready(out)
        done += 1
        if time.monotonic() - w0 > w_budget:
            _partial({"warmup_truncated": done})
            break
    _partial({"warmup_s": round(time.monotonic() - w0, 2)})
    _phase(f"{metric}:throughput")

    events_per_sec = 0.0
    for _rep in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            out = run_step(i)
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - t0
        events_per_sec = max(events_per_sec, events_per_step * steps / elapsed)

    _phase(f"{metric}:p99")
    lat = []
    n_lat = LAT_STEPS
    for i in range(LAT_STEPS):
        t0 = time.perf_counter()
        out = run_step(i)
        jax.block_until_ready(out)
        lat.append(time.perf_counter() - t0)
        if i == 0 and lat[0] > 0.2:
            # slow-host guard: 50 synchronous 500 ms steps would eat the
            # watchdog budget; a >=10-sample p99 still bounds the tail
            n_lat = max(10, LAT_STEPS // 5)
        if i + 1 >= n_lat:
            break
    lat_arr = np.array(lat)
    p99_ms = float(np.percentile(lat_arr, 99) * 1e3)
    p50_ms = float(np.percentile(lat_arr, 50) * 1e3)

    baseline = _baseline_for(metric)
    res = {
        "metric": metric,
        "value": round(events_per_sec, 1),
        "unit": "events/sec",
        "vs_baseline": round(events_per_sec / baseline, 3),
        "device_step_ms": round(events_per_step * 1e3 / events_per_sec, 4),
        "p99_batch_latency_ms": round(p99_ms, 3),
        # first-class percentile fields for every config; e2e runs
        # overwrite them with true ingest→delivery numbers from the
        # telemetry histograms (_e2e_latency_fields)
        "p50_latency_ms": round(p50_ms, 3),
        "p99_latency_ms": round(p99_ms, 3),
    }
    _partial(res)
    return res


def _e2e_latency_fields(rt) -> dict:
    """p50/p99 end-to-end batch latency (mint-at-ingress → delivery end)
    from the always-on telemetry stage histograms, merged across streams."""
    from siddhi_tpu.telemetry.metrics import N_BUCKETS, quantile_from_buckets
    tele = getattr(rt.ctx, "telemetry", None)
    if tele is None or not tele.on:
        return {}
    buckets = [0] * N_BUCKETS
    count = 0
    for (_stream, stage), hist in tele.stage_hist.samples():
        if stage != "e2e":
            continue
        b, c, _ = hist.snapshot()
        for i in range(N_BUCKETS):
            buckets[i] += b[i]
        count += c
    if not count:
        return {}
    return {
        "p50_latency_ms":
            round(quantile_from_buckets(buckets, count, 0.5) / 1e6, 3),
        "p99_latency_ms":
            round(quantile_from_buckets(buckets, count, 0.99) / 1e6, 3),
    }


#: p50/p99 of the most recent _measure_e2e run (merged into the config's
#: result dict by each caller)
_E2E_LAT: dict = {}


def _measure_e2e(rt, out_stream: str, feed_round, events_per_round: int,
                 *, rounds: int = 8, warmup: int = 2,
                 columnar: bool = True) -> float:
    """End-to-end throughput through the PUBLIC ingestion path:
    InputHandler.send_columns (or send_batch for the rows variant) → host
    encode (native C, interning) → junction → jitted step → async callback
    delivery. `columnar=True` subscribes a ColumnarBlock callback (the
    batch-level Event[] analogue); False materializes per-row Event objects.
    The clock stops at drain() — every produced event has been decoded and
    delivered to the callback before elapsed is read, so async decode
    pipelines the device→host round trips but cannot hide undone work."""
    # bench-time chaos soak: SIDDHI_FAULT_SPEC (e.g. "sink:p=0.01,seed=7")
    # injects seeded faults into the runtime's transports so sustained
    # throughput is measured THROUGH the retry/dead-letter paths, not only
    # on the sunny day (siddhi_tpu/util/faults.py documents the grammar)
    fault_plans = {}
    if os.environ.get("SIDDHI_FAULT_SPEC"):
        from siddhi_tpu.util.faults import apply_fault_spec
        fault_plans = apply_fault_spec(rt)
    if _is_cpu():
        rounds = max(2, rounds // 2)  # see _measure's CPU shrink
    n_out = [0]
    if columnar:
        rt.add_callback(out_stream, lambda blk: n_out.__setitem__(
            0, n_out[0] + blk.count), columnar=True)
    else:
        rt.add_callback(out_stream, lambda evs: n_out.__setitem__(
            0, n_out[0] + len(evs)))
    _phase(f"e2e:{out_stream}:aot_warmup")
    t_w = time.monotonic()
    rt.start()
    # AOT-warm the FULL-WIDTH bucket only: the e2e feed sends exact
    # full-capacity batches (no auto-flush, no heartbeats), so batch_size
    # is the single shape this run dispatches — warming more rungs of a
    # 1M-group aggregation step repeats its dominant (group-capacity)
    # compile cost for shapes never hit
    caps = {j.batch_size for j in rt.junctions.values()}
    rt.warmup(tuple(sorted(caps)))
    _partial({"aot_warmup_s": round(time.monotonic() - t_w, 2)})
    _phase(f"e2e:{out_stream}:feed")
    for r in range(warmup):
        feed_round(r)
    rt.drain()
    best = 0.0
    r0 = warmup
    for _rep in range(3):  # best-of-3: the tunnel's throughput drifts
        t0 = time.perf_counter()
        for r in range(r0, r0 + rounds):
            feed_round(r)
        rt.drain()
        elapsed = time.perf_counter() - t0
        r0 += rounds
        best = max(best, events_per_round * rounds / elapsed)
    _E2E_LAT.clear()
    _E2E_LAT.update(_e2e_latency_fields(rt))
    rt.shutdown()
    if fault_plans:
        _partial({"fault_injection": {
            t: {"calls": p.calls, "fired": p.fired}
            for t, p in fault_plans.items()}})
    assert n_out[0] > 0, "e2e run produced no output — not a valid measure"
    return best


def _measure_autoflush_p99(app: str, *, rate_hz: float = 1000.0,
                           seconds: float = 2.0) -> float:
    """p99 send→callback latency at a LOW event rate with auto-flush: the
    caller never calls flush(); the runtime's wall-clock flusher must bound
    staged latency (target < 50 ms co-located)."""
    from siddhi_tpu import SiddhiManager

    rt = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=256, auto_flush_ms=10, aot_warmup=True)
    lat: list = []
    pend: dict = {}

    def cb(evs):
        t = time.perf_counter()
        for e in evs:
            s = pend.pop(e.data[1], None)
            if s is not None:
                lat.append((t - s) * 1e3)

    rt.add_callback(next(
        ln.split("insert into ")[1].split(";")[0].strip()
        for ln in app.splitlines() if "insert into" in ln), cb)
    rt.start()
    h = rt.get_input_handler("TradeStream")
    for i in range(5):  # warm the partial-batch compile out of the measure
        h.send(("WARM", 1e9 + i, 1))
        time.sleep(0.05)
    v = 1.0
    t_end = time.perf_counter() + seconds
    while time.perf_counter() < t_end:
        pend[v] = time.perf_counter()
        h.send(("S1", v, 1))
        v += 1.0
        time.sleep(1.0 / rate_hz)
    time.sleep(0.2)
    rt.shutdown()
    if not lat:
        return float("inf")
    lat.sort()
    return round(lat[min(int(len(lat) * 0.99), len(lat) - 1)], 2)


def _trade_rows(n_rounds: int, n_keys: int, *, price_hi: float = 100.0,
                n: int = BATCH):
    """Host python rows (string symbols) for the e2e rows-path variant."""
    rng = np.random.default_rng(RNG_SEED + 1)
    rounds = []
    for _ in range(n_rounds):
        ks = rng.integers(1, n_keys + 1, n)
        ps = rng.uniform(1.0, price_hi, n)
        vs = rng.integers(1, 1000, n)
        rounds.append([(f"S{int(k)}", float(p), int(v))
                       for k, p, v in zip(ks, ps, vs)])
    return rounds


def _trade_cols(n_rounds: int, n_keys: int, *, price_hi: float = 100.0,
                n: int = BATCH):
    """Columnar public-path feed: numpy columns per round. Symbols are
    Python string objects drawn from a pooled universe — the realistic
    producer shape (market-data handlers intern their symbol strings), and
    what the native encoder's pointer-identity memo is built for."""
    rng = np.random.default_rng(RNG_SEED + 1)
    pool = np.array([f"S{i}" for i in range(1, n_keys + 1)], dtype=object)
    rounds = []
    for _ in range(n_rounds):
        ks = rng.integers(0, n_keys, n)
        rounds.append({
            "symbol": pool[ks],
            "price": rng.uniform(1.0, price_hi, n),
            "volume": rng.integers(1, 1000, n),
        })
    return rounds


def _trade_batches(n: int, n_keys: int, *, ms_per_event: int = 0,
                   price_hi: float = 100.0):
    from siddhi_tpu.core.event import EventBatch

    rng = np.random.default_rng(RNG_SEED)
    batches, ts0 = [], 1
    for _ in range(n):
        if ms_per_event:
            ts = np.arange(ts0, ts0 + BATCH * ms_per_event, ms_per_event,
                           dtype=np.int64)
            ts0 += BATCH * ms_per_event
        else:
            ts = np.arange(ts0, ts0 + BATCH, dtype=np.int64)
            ts0 += BATCH
        cols = {
            # pre-encoded dictionary codes (1..n_keys); code 0 is null
            "symbol": rng.integers(1, n_keys + 1, BATCH, dtype=np.int32),
            "price": rng.uniform(1.0, price_hi, BATCH).astype(np.float32),
            "volume": rng.integers(1, 1000, BATCH, dtype=np.int64),
        }
        batches.append(EventBatch.from_numpy(ts, cols, BATCH))
    return batches, ts0


# --------------------------------------------------------------------- configs


def bench_filter() -> dict:
    """BASELINE config 1: single filter+project (reference:
    SimpleFilterSingleQueryPerformance.java:40-52, `700 > price`)."""
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager

    app = """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream[700.0 > price]
    select symbol, price
    insert into OutStream;
    """
    if E2E_ONLY:
        res = {"metric": "filter_events_per_sec"}
    else:
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=BATCH)
        qr = rt.query_runtimes["bench"]
        batches, ts_end = _trade_batches(8, 1000, price_hi=1000.0)
        state = [qr.state]

        def run(i):
            state[0], out = qr._step(state[0], batches[i % len(batches)],
                                     jnp.int64(ts_end))
            return out

        res = _measure(run, BATCH, "filter_events_per_sec")

    rt2 = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=E2E_BATCH, async_callbacks=True)
    cols = _trade_cols(4, 1000, price_hi=1000.0, n=E2E_BATCH)
    h = rt2.get_input_handler("TradeStream")

    def feed(r):
        h.send_columns(cols[r % len(cols)])
        rt2.flush()

    res["e2e_events_per_sec"] = round(
        _measure_e2e(rt2, "OutStream", feed, E2E_BATCH), 1)
    res.update(_E2E_LAT)
    _partial({"e2e_events_per_sec": res["e2e_events_per_sec"], **_E2E_LAT})

    # auto-flush latency at LOW rate (1k ev/s, no flush() from the caller):
    # the wall-clock flusher bounds staged latency (VERDICT r04 item 5;
    # reference role: the Disruptor's immediate consumption)
    _phase("filter:autoflush_p99")
    res["p99_autoflush_latency_ms"] = _measure_autoflush_p99(app)
    _partial({"p99_autoflush_latency_ms": res["p99_autoflush_latency_ms"]})

    if not E2E_ONLY:  # secondary: row-at-a-time public API
        rt3 = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=E2E_BATCH, async_callbacks=True)
        rows = _trade_rows(4, 1000, price_hi=1000.0, n=E2E_BATCH)
        h3 = rt3.get_input_handler("TradeStream")

        def feed_rows(r):
            h3.send_batch(rows[r % len(rows)])
            rt3.flush()

        res["e2e_rows_events_per_sec"] = round(
            _measure_e2e(rt3, "OutStream", feed_rows, E2E_BATCH,
                         columnar=False, rounds=4), 1)
        _partial({"e2e_rows_events_per_sec": res["e2e_rows_events_per_sec"]})
        res.update(_preflight(app))
    return res


def bench_groupby() -> dict:
    """BASELINE config 2 (headline): lengthBatch(10000) sum/avg group-by, 1M keys."""
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager

    app = """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """
    if E2E_ONLY:
        res = {"metric": "lengthBatch10k_groupby_1M_keys_events_per_sec"}
    else:
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=BATCH, group_capacity=1 << 20)
        qr = rt.query_runtimes["bench"]
        batches, ts_end = _trade_batches(8, 1_000_000)
        state = [qr.state]

        def run(i):
            state[0], out = qr._step(state[0], batches[i % len(batches)],
                                     jnp.int64(ts_end))
            return out

        res = _measure(run, BATCH,
                       "lengthBatch10k_groupby_1M_keys_events_per_sec")

    rt2 = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=E2E_BATCH, group_capacity=1 << 20,
        async_callbacks=True)
    cols = _trade_cols(4, 1_000_000, n=E2E_BATCH)
    h = rt2.get_input_handler("TradeStream")

    def feed(r):
        h.send_columns(cols[r % len(cols)])
        rt2.flush()

    res["e2e_events_per_sec"] = round(
        _measure_e2e(rt2, "SummaryStream", feed, E2E_BATCH), 1)
    res.update(_E2E_LAT)
    _partial({"e2e_events_per_sec": res["e2e_events_per_sec"], **_E2E_LAT})
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def bench_distinct() -> dict:
    """BASELINE config 3: 60-sec sliding time window, exact distinctCount.
    ~1 ms event spacing -> the window holds ~60k events in steady state."""
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager

    app = """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.time(60 sec)
    select distinctCount(symbol) as distinctSymbols
    insert into OutStream;
    """
    if E2E_ONLY:
        res = {"metric": "sliding60s_distinctCount_events_per_sec"}
        return _distinct_e2e(app, res)
    # lifetime-unique values bounded (100k) well under the 1M pair capacity
    rt = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=BATCH, group_capacity=1 << 20)
    qr = rt.query_runtimes["bench"]
    # timestamps must keep advancing monotonically across ALL phases
    # (warmup, 3 throughput reps, latency loop) or the 60 s window drains
    # and the watermark regresses. Build every step's batch host-side:
    # feeding device-computed arrays (e.g. a device-side ts shift) into a
    # step serializes the tunnel's async dispatch (~13 ms/step artifact),
    # while host-built batches pipeline — and host batches are what the
    # real ingestion path produces.
    n_steps = WARMUP + 3 * STEPS + LAT_STEPS + 8
    batches, _ = _trade_batches(n_steps, 100_000, ms_per_event=1)
    state = [qr.state]
    step_no = [0]

    def run(_i):
        k = step_no[0]
        step_no[0] += 1
        b = batches[k]
        now = jnp.int64((k + 1) * BATCH)
        state[0], out = qr._step(state[0], b, now)
        return out

    res = _measure(run, BATCH, "sliding60s_distinctCount_events_per_sec")
    return _distinct_e2e(app, res)


def _distinct_e2e(app: str, res: dict) -> dict:
    from siddhi_tpu import SiddhiManager

    rt2 = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=E2E_BATCH, group_capacity=1 << 20,
        async_callbacks=True)
    cols = _trade_cols(4, 100_000, n=E2E_BATCH)
    h = rt2.get_input_handler("TradeStream")
    ts_ctr = [1]

    def feed(r):
        t = ts_ctr[0]
        ts_ctr[0] = t + E2E_BATCH
        h.send_columns(cols[r % len(cols)],
                       timestamps=np.arange(t, t + E2E_BATCH,
                                            dtype=np.int64))
        rt2.flush()

    res["e2e_events_per_sec"] = round(
        _measure_e2e(rt2, "OutStream", feed, E2E_BATCH), 1)
    res.update(_E2E_LAT)
    _partial({"e2e_events_per_sec": res["e2e_events_per_sec"], **_E2E_LAT})
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def bench_pattern() -> dict:
    """BASELINE config 4: `every a=A -> b=B[b.val == a.val] within 5 sec`.
    Alternating A/B batches; every B consumes exactly one pending A."""
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core import dtypes
    from siddhi_tpu.core.event import EventBatch

    # device NFA time is sub-ms; tunnel dispatch overhead dominates at small
    # batches, so run full-width batches with pending capacity to match
    # device NFA width: full batch through the tunnel; on CPU both the
    # compile and the per-step cost of the 4x-pending NFA grow with width —
    # a narrower batch keeps the config inside its watchdog budget on
    # small hosts (same engine path)
    pb = BATCH if not _is_cpu() else 512
    app = """
    define stream StreamA (val int);
    define stream StreamB (val int);
    @info(name = 'bench')
    from every a=StreamA -> b=StreamB[b.val == a.val] within 5 sec
    select a.val as aVal, b.val as bVal
    insert into OutStream;
    """
    if E2E_ONLY:
        res = {"metric": "pattern_everyAB_within5s_events_per_sec"}
    else:
        prev_cap = dtypes.config.pattern_pending_capacity
        dtypes.config.pattern_pending_capacity = 4 * pb
        try:
            rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=pb)
            qr = rt.query_runtimes["bench"]
        finally:
            dtypes.config.pattern_pending_capacity = prev_cap

        n_cycles = 4
        ab = []
        ts0 = 1
        for k in range(n_cycles):
            vals = np.arange(k * pb, (k + 1) * pb, dtype=np.int32)
            ts_a = np.arange(ts0, ts0 + pb, dtype=np.int64)
            a = EventBatch.from_numpy(ts_a, {"val": vals}, pb)
            ts_b = ts_a + pb
            b = EventBatch.from_numpy(ts_b, {"val": vals}, pb)
            ts0 += 2 * pb
            ab.append((a, b, ts0 - 1))
        state = [qr.state]

        def run(i):
            a, b, now = ab[i % n_cycles]
            state[0], _ = qr._steps["StreamA"](state[0], a, jnp.int64(now - pb))
            state[0], out = qr._steps["StreamB"](state[0], b, jnp.int64(now))
            return out

        res = _measure(run, 2 * pb, "pattern_everyAB_within5s_events_per_sec")

    # e2e batch: amortizes the per-batch readback round trips (tunnel);
    # CPU shrinks with the device width (no tunnel, cheaper steps)
    eb = 32768 if not _is_cpu() else 2048
    prev_cap = dtypes.config.pattern_pending_capacity
    dtypes.config.pattern_pending_capacity = 4 * eb
    try:
        rt2 = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=eb, async_callbacks=True)
    finally:
        dtypes.config.pattern_pending_capacity = prev_cap
    ha = rt2.get_input_handler("StreamA")
    hb = rt2.get_input_handler("StreamB")
    val_ctr = [0]

    def feed(r):
        v0 = val_ctr[0]
        val_ctr[0] += eb
        vals = np.arange(v0, v0 + eb, dtype=np.int32)
        ha.send_columns({"val": vals})
        rt2.flush()
        hb.send_columns({"val": vals})
        rt2.flush()

    res["e2e_events_per_sec"] = round(
        _measure_e2e(rt2, "OutStream", feed, 2 * eb), 1)
    res.update(_E2E_LAT)
    _partial({"e2e_events_per_sec": res["e2e_events_per_sec"], **_E2E_LAT})
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def bench_join() -> dict:
    """BASELINE config 5: equi join over two length(100000) windows; keys
    uniform over 100k so each probe matches ~1 build row."""
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.event import EventBatch

    app = """
    define stream LeftStream (k int, v double);
    define stream RightStream (k int, v double);
    @info(name = 'bench')
    from LeftStream#window.length(100000) as a
    join RightStream#window.length(100000) as b
    on a.k == b.k
    select a.k as k, a.v as lv, b.v as rv
    insert into OutStream;
    """
    if E2E_ONLY:
        res = {"metric": "join_100kx100k_events_per_sec"}
    else:
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=BATCH)
        qr = rt.query_runtimes["bench"]

        rng = np.random.default_rng(RNG_SEED)
        n_distinct = 8
        lr = []
        ts0 = 1
        for _ in range(n_distinct):
            ts = np.arange(ts0, ts0 + BATCH, dtype=np.int64)
            ts0 += BATCH
            mk = lambda: {"k": rng.integers(1, 100_001, BATCH, dtype=np.int32),
                          "v": rng.uniform(1.0, 100.0, BATCH).astype(np.float32)}
            lr.append((EventBatch.from_numpy(ts, mk(), BATCH),
                       EventBatch.from_numpy(ts, mk(), BATCH)))
        state = [qr.state]

        def run(i):
            l, r = lr[i % n_distinct]
            now = jnp.int64(ts0)
            state[0], _, _ = qr._step_left(state[0], l, now, None)
            state[0], out, _ = qr._step_right(state[0], r, now, None)
            return out

        res = _measure(run, 2 * BATCH, "join_100kx100k_events_per_sec")

    # join e2e stays at the device batch: the join's OUTPUT block scales
    # with pair_cap_factor x B, so larger input batches inflate the per-batch
    # readback superlinearly (measured: 8192 beats 16k/32k through the wire)
    jb = BATCH
    rt2 = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=jb, async_callbacks=True)
    rng2 = np.random.default_rng(RNG_SEED + 1)
    rounds = []
    for _ in range(4):
        mk = lambda: {"k": rng2.integers(1, 100_001, jb).astype(np.int32),
                      "v": rng2.uniform(1.0, 100.0, jb)}
        rounds.append((mk(), mk()))
    hl = rt2.get_input_handler("LeftStream")
    hr = rt2.get_input_handler("RightStream")

    def feed(r):
        lcols, rcols = rounds[r % len(rounds)]
        hl.send_columns(lcols)
        rt2.flush()
        hr.send_columns(rcols)
        rt2.flush()

    res["e2e_events_per_sec"] = round(
        _measure_e2e(rt2, "OutStream", feed, 2 * jb), 1)
    res.update(_E2E_LAT)
    _partial({"e2e_events_per_sec": res["e2e_events_per_sec"], **_E2E_LAT})
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def bench_overload() -> dict:
    """Satellite config: sustained throughput UNDER overload — a producer
    running ~10x faster than a deliberately slowed consumer into a bounded
    `@Async(overflow.policy='drop.old')` stream. Reports the delivered
    (sustained) rate plus exact drop counts, and asserts conservation:
    every sent event was delivered, dropped-by-policy, or counted at
    shutdown — bounded ingress may shed load but never silently."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.util.faults import FaultPlan, inject

    res = {"metric": "overload_sustained_events_per_sec"}
    if E2E_ONLY:  # no tunnel/topology split for this config
        return res
    app = """
    @app:name('Overload')
    @Async(buffer.size='256', overflow.policy='drop.old', max.staged='1024')
    define stream TradeStream (v long);
    @info(name = 'bench')
    from TradeStream select v insert into OutStream;
    """
    rt = SiddhiManager().create_siddhi_app_runtime(app)
    delivered = [0]
    rt.add_callback("OutStream", lambda blk: delivered.__setitem__(
        0, delivered[0] + blk.count), columnar=True)
    # the slow consumer: every query step stalls 2 ms (seeded, always due),
    # capping consumption at ~128k ev/s while the producer pushes millions
    qr = rt.query_runtimes["bench"]
    inject(qr, "on_batch", FaultPlan(p=1.0, seed=RNG_SEED, slow_s=0.002))
    rt.start()
    h = rt.get_input_handler("TradeStream")
    rows = [(int(i),) for i in range(256)]

    _phase("overload:warmup")
    h.send_batch(rows)
    t0 = time.monotonic()
    while delivered[0] == 0 and time.monotonic() - t0 < CONFIG_SECONDS / 2:
        time.sleep(0.01)  # first batch through = compile done
    sent = 256

    _phase("overload:feed")
    t0 = time.perf_counter()
    t_end = t0 + 4.0
    while time.perf_counter() < t_end:
        h.send_batch(rows)
        sent += 256
    rt.flush()
    rt.shutdown()  # drains whatever is still staged
    elapsed = time.perf_counter() - t0

    rep = rt.statistics_report()
    drops = rep["ingress_dropped"].get("TradeStream", {})
    dropped = sum(drops.values())
    discarded = rep["recovery"]["shutdown_discarded"]
    res.update({
        "value": round(delivered[0] / elapsed, 1),
        "unit": "events/sec",
        "vs_baseline": round(
            delivered[0] / elapsed
            / _baseline_for("overload_sustained_events_per_sec"), 3),
        "sent": sent,
        "dropped": dropped,
        "drop_rate": round(dropped / max(sent, 1), 4),
        "queue_hwm": rep["backpressure"]["queue_hwm"].get("TradeStream", 0),
        "conservation_ok":
            delivered[0] + dropped + discarded == sent,
    })
    _partial(res)
    res.update(_preflight(app))
    return res


def bench_disorder() -> dict:
    """Satellite config: out-of-order ingress through the @app:eventTime
    gate (core/event_time.py). A seeded bounded-disorder permutation (the
    shuffled-replay oracle's model: displacement < allowed.lateness) feeds
    the gate, with a deliberate 1-in-128 straggler BEYOND the budget.
    Reports the sustained gated rate, the displaced-row share, exact late
    diversions (must equal the injected stragglers — zero silent drops),
    and the gate's conservation identity."""
    import random as _random

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.upgrade import _bounded_shuffle

    res = {"metric": "disorder_gated_events_per_sec"}
    if E2E_ONLY:  # host-side gate: no tunnel/topology split
        return res
    app = """
    @app:name('Disorder')
    @app:eventTime(timestamp='ts', allowed.lateness='50')
    define stream TradeStream (ts long, v long);
    @info(name = 'bench')
    from TradeStream select ts, v insert into OutStream;
    """
    rt = SiddhiManager().create_siddhi_app_runtime(app)
    delivered = [0]
    rt.add_callback("OutStream", lambda blk: delivered.__setitem__(
        0, delivered[0] + blk.count), columnar=True)
    rt.start()
    h = rt.get_input_handler("TradeStream")

    # sensor-fleet shape: 16 rows per 10 ms event-time tick (so per-ts
    # delivery groups stay batch-sized), displaced by the oracle's bounded
    # shuffle; epoch-ms base keeps the telemetry plausibility window open
    epoch = 1_700_000_000_000
    n_pre, per_tick, batch = 8192, 16, 256
    ordered = [("S", epoch + (i // per_tick) * 10,
                (epoch + (i // per_tick) * 10, i)) for i in range(n_pre)]
    shuffled = _bounded_shuffle(ordered, 50, RNG_SEED)
    displaced = sum(1 for a, b in zip(ordered, shuffled) if a is not b)
    rng = _random.Random(RNG_SEED)
    rows, stragglers = [], 0
    for _sid, ts, row in shuffled:
        if rng.randrange(128) == 0:  # beyond-budget straggler: must divert
            rows.append((ts - 10_000, row[1]))
            stragglers += 1
        else:
            rows.append(row)
    batches = [rows[i:i + batch] for i in range(0, len(rows), batch)]

    _phase("disorder:warmup")
    h.send_batch(batches[0])
    rt.flush()
    sent = len(batches[0])

    _phase("disorder:feed")
    t0 = time.perf_counter()
    t_end = t0 + 4.0
    loops = 0
    while time.perf_counter() < t_end:
        cycle, idx = divmod(loops, len(batches) - 1)
        b = batches[1 + idx]
        if cycle:
            # each recycle re-bases event time above the released horizon
            # so recycled batches don't all classify late
            shift = cycle * 100_000_000
            b = [(ts + shift, v) for ts, v in b]
        h.send_batch(b)
        rt.flush()
        sent += len(b)
        loops += 1
    rt.release_watermarks()
    elapsed = time.perf_counter() - t0
    rt.shutdown()

    wm = rt.statistics_report()["watermarks"]["TradeStream"]
    expected_late = stragglers * max(1, loops // max(1, len(batches) - 1))
    res.update({
        "value": round(delivered[0] / elapsed, 1),
        "unit": "events/sec",
        "vs_baseline": round(
            delivered[0] / elapsed
            / _baseline_for("disorder_gated_events_per_sec"), 3),
        "sent": sent,
        "displaced_share": round(displaced / n_pre, 3),
        "lateness_ms": 50,
        "late_diverted": wm["late"],
        "late_expected_about": expected_late,
        "buffered_after_drain": wm["buffered"],
        "conservation_ok":
            wm["admitted"] == wm["released"] + wm["late"] + wm["buffered"]
            and wm["buffered"] == 0
            and delivered[0] == wm["released"],
    })
    _partial(res)
    res.update(_preflight(app))
    return res


def bench_upgrade() -> dict:
    """Satellite config: blue-green hot-swap (core/upgrade.py) committed in
    the middle of sustained public-path traffic. Reports the source-paused
    (cutover) window — the only span where ingress stalls — and proves exact
    conservation: every event sent before, during, and after the swap is
    delivered exactly once (count AND checksum), by exactly one version."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.state.persistence import InMemoryPersistenceStore

    res = {"metric": "upgrade_cutover_pause_ms"}
    if E2E_ONLY:  # no tunnel/topology split for this config
        return res
    app_v1 = """
    @app:name('UpgradeBench')
    define stream TradeStream (v long);
    @info(name = 'bench')
    from TradeStream select v insert into OutStream;
    """
    app_v2 = app_v1 + """
    @info(name = 'mirror')
    from TradeStream select v insert into MirrorStream;
    """
    mgr = SiddhiManager()
    mgr.set_persistence_store(InMemoryPersistenceStore())
    rt = mgr.create_siddhi_app_runtime(app_v1, batch_size=1024)
    delivered = [0, 0]  # count, checksum — dupes+losses can't cancel both

    def cb(evs):
        delivered[0] += len(evs)
        delivered[1] += sum(e.data[0] for e in evs)

    rt.add_callback("OutStream", cb)  # migrates with the swap
    rt.start()
    h = rt.get_input_handler("TradeStream")

    _phase("upgrade:warmup")
    h.send_batch([(int(i),) for i in range(1024)])
    rt.flush()
    rt.drain()
    sent, checksum = 1024, sum(range(1024))

    _phase("upgrade:feed")
    summary: dict = {}
    stop = threading.Event()

    def swap():  # mid-stream, against live producer traffic
        time.sleep(0.5)
        summary.update(mgr.upgrade(app_v2))
        stop.set()

    sw = threading.Thread(target=swap, name="bench-upgrade-swap")
    sw.start()
    t0 = time.perf_counter()
    v = sent
    while not stop.is_set() or time.perf_counter() - t0 < 1.5:
        rows = [(int(i),) for i in range(v, v + 256)]
        h.send_batch(rows)  # stale v1 handle: forwards through the redirect
        sent += 256
        checksum += sum(range(v, v + 256))
        v += 256
        mgr.runtimes["UpgradeBench"].flush()
        if time.perf_counter() - t0 > CONFIG_SECONDS / 3:
            break  # watchdog floor — partials still conserve
    sw.join()
    elapsed = time.perf_counter() - t0
    rt2 = mgr.runtimes["UpgradeBench"]
    rt2.drain()
    rt2.shutdown()

    rep = rt2.statistics_report()["upgrade"]
    res.update({
        "value": round(summary.get("cutover_pause_ms", 0.0), 3),
        "unit": "ms",
        "classification": summary.get("classification"),
        "wal_tail_replayed": summary.get("wal_tail_replayed"),
        "sent": sent,
        "delivered": delivered[0],
        "checksum_ok": delivered[1] == checksum,
        "conserved": delivered[0] == sent and delivered[1] == checksum,
        "events_per_sec_through_swap": round((sent - 1024) / elapsed, 1),
        "upgrades": rep["upgrades"],
    })
    _partial(res)
    res.update(_preflight(app_v1))
    return res


def bench_e2e_ingress() -> dict:
    """HEADLINE config: multi-producer SXF1 binary ingestion through the
    service surface (SiddhiService.send_frames — the REST frames endpoint's
    exact code path minus the socket) into an @Async(workers=N) filter →
    lengthBatch group-by app. This engages the full parallel-ingress
    pipeline: lock-free columnar ring claim, GIL-released decode workers,
    ticket-ordered dictionary interning, double-buffered device feeds. The
    per-stage breakdown (decode/intern/h2d/device ms) and overlap ratio
    come from the always-on statistics_report()["ingress_pipeline"]
    section, so a regression in any one stage is visible next to the
    headline rate.

    Swept over superstep depth K in {1, 8, 32} (@app:superstep — one
    lax.scan dispatch + one output fetch per K staged batches,
    core/superstep.py) on fresh runtimes; the headline is the best K and
    each K reports its own p99 so the throughput/latency trade is visible
    in one record."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.io import wire
    from siddhi_tpu.service import SiddhiService

    eb = _resolve_e2e_batch()
    cpu = _is_cpu()
    n_producers = 2 if cpu else 4
    n_workers = 2 if cpu else 4
    n_keys = 10_000

    def app_text(k: int) -> str:
        ss = f"@app:superstep(k='{k}')\n    " if k > 1 else ""
        return f"""
    @app:name('IngressBench')
    {ss}@app:slo(stream='TradeStream', p99.ms='60000')
    @Async(buffer.size='{eb}', workers='{n_workers}')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """

    app = app_text(1)

    def build_stack(k: int):
        mgr_x = SiddhiManager()
        rt_x = mgr_x.create_siddhi_app_runtime(
            app_text(k), batch_size=eb, group_capacity=1 << 17,
            async_callbacks=True)
        svc_x = SiddhiService(mgr_x)
        n_out_x = [0]
        rt_x.add_callback("SummaryStream", lambda blk: n_out_x.__setitem__(
            0, n_out_x[0] + blk.count), columnar=True)
        rt_x.start()
        rt_x.warmup(tuple(sorted(
            {j.batch_size for j in rt_x.junctions.values()})))
        return mgr_x, rt_x, svc_x, n_out_x

    _phase("e2e_ingress:aot_warmup")
    t_w = time.monotonic()
    mgr, rt, svc, n_out = build_stack(1)
    _partial({"aot_warmup_s": round(time.monotonic() - t_w, 2)})

    _phase("e2e_ingress:encode")
    # pre-encoded frame bodies: producer-side dictionary encoding means the
    # server interns per DISTINCT symbol (~n_keys), not per row
    plan = wire.schema_plan(rt.junctions["TradeStream"].definition)
    rng = np.random.default_rng(RNG_SEED + 2)
    bodies = []
    for _p in range(n_producers):
        per = []
        for _ in range(3):
            ks = rng.integers(1, n_keys + 1, eb)
            cols = {
                "symbol": np.array([f"S{int(k)}" for k in ks], dtype=object),
                "price": rng.uniform(1.0, 1000.0, eb),
                "volume": rng.integers(1, 1000, eb),
            }
            per.append(wire.encode_frames(plan, cols, eb))
        bodies.append(per)

    def measure(svc_x, rt_x, rounds: int) -> float:
        def producer(p: int, n_rounds: int, r0: int) -> None:
            per = bodies[p]
            for r in range(n_rounds):
                svc_x.send_frames("IngressBench", "TradeStream",
                                  per[(r0 + r) % len(per)])

        def run_rounds(n_rounds: int, r0: int) -> None:
            threads = [threading.Thread(target=producer,
                                        args=(p, n_rounds, r0),
                                        name=f"bench-producer-{p}")
                       for p in range(n_producers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            rt_x.drain()  # clock stops only after every event is delivered

        run_rounds(2, 0)
        best_x = 0.0
        r0 = 2
        for _rep in range(3):
            t0 = time.perf_counter()
            run_rounds(rounds, r0)
            elapsed = time.perf_counter() - t0
            r0 += rounds
            best_x = max(best_x, n_producers * rounds * eb / elapsed)
        return best_x

    _phase("e2e_ingress:feed")
    rounds = 2 if cpu else 6
    sweep: dict = {}
    best = 0.0
    best_k = 1
    best_pipe: dict = {}
    best_lat: dict = {}
    for k in (1, 8, 32):
        _phase(f"e2e_ingress:feed_k{k}")
        if k == 1:
            mgr_k, rt_k, svc_k, n_out_k = mgr, rt, svc, n_out
        else:
            mgr_k, rt_k, svc_k, n_out_k = build_stack(k)
        # a superstep stages K ring chunks before one scan dispatch, so
        # each rep must push well past K full batches or K=32 would
        # measure only the per-chunk flush fallback
        rounds_k = max(rounds, (3 * k + n_producers - 1) // n_producers)
        rate_k = measure(svc_k, rt_k, rounds_k)
        rep_k = rt_k.statistics_report()  # before shutdown: stop detaches
        pipe_k = rep_k.get("ingress_pipeline", {}).get("TradeStream", {})
        lat_k = _e2e_latency_fields(rt_k)
        rt_k.shutdown()
        assert n_out_k[0] > 0, \
            f"e2e_ingress k={k} produced no output — not a valid measure"
        if k > 1:
            assert pipe_k.get("supersteps_dispatched", 0) > 0, (
                f"superstep k={k} never engaged: "
                f"{pipe_k.get('superstep_decline')}")
        sweep[k] = {"events_per_sec": round(rate_k, 1),
                    "supersteps_dispatched":
                        pipe_k.get("supersteps_dispatched", 0),
                    "superstep_scan_ms":
                        round(pipe_k.get("superstep_scan_ms", 0.0), 1),
                    "superstep_replay_ms":
                        round(pipe_k.get("superstep_replay_ms", 0.0), 1),
                    **lat_k}
        _partial({f"superstep_k{k}_events_per_sec": round(rate_k, 1),
                  f"superstep_k{k}_p99_latency_ms":
                      lat_k.get("p99_latency_ms")})
        if rate_k > best:
            best, best_k, best_pipe, best_lat = rate_k, k, pipe_k, lat_k

    stage = best_pipe.get("stage_ms", {})

    def _mean(name: str):
        cell = stage.get(name) or {}
        return cell.get("mean_ms")

    value = round(best, 1)
    res = {
        "metric": "e2e_ingress_events_per_sec",
        "value": value,
        "unit": "events/sec",
        "vs_baseline": round(
            value / _baseline_for("e2e_ingress_events_per_sec"), 3),
        "e2e_events_per_sec": value,
        "producers": n_producers,
        "ingress_workers": n_workers,
        # superstep sweep: headline is the best K; each K keeps its own
        # p99 so the dispatch-amortization vs batching-delay trade is
        # visible in one record (docs/OBSERVABILITY.md)
        "superstep_best_k": best_k,
        "superstep_k1_events_per_sec": sweep[1]["events_per_sec"],
        "superstep_k8_events_per_sec": sweep[8]["events_per_sec"],
        "superstep_k32_events_per_sec": sweep[32]["events_per_sec"],
        "superstep_k1_p99_latency_ms": sweep[1].get("p99_latency_ms"),
        "superstep_k8_p99_latency_ms": sweep[8].get("p99_latency_ms"),
        "superstep_k32_p99_latency_ms": sweep[32].get("p99_latency_ms"),
        "superstep_sweep": sweep,
        # per-stage means (per worker run / per batch) — the satellite fix
        # replaced bare cumulative totals with {total_ms, batches, mean_ms}
        "decode_mean_ms": _mean("decode"),
        "intern_mean_ms": _mean("intern"),
        "h2d_mean_ms": _mean("h2d"),
        "device_mean_ms": _mean("device"),
        "stage_ms": stage,
        "h2d_overlap_ratio": best_pipe.get("h2d_overlap_ratio"),
        "worker_utilization": best_pipe.get("worker_utilization"),
        "ring_depth_hwm": best_pipe.get("ring_depth_hwm"),
        **best_lat,
    }
    _partial(res)

    # telemetry overhead A/B: identical workload with SIDDHI_TELEMETRY=0
    # (span recording off at AppTelemetry creation — which also disables
    # the @app:slo engine, so the ON side carries tracing + SLO ticks +
    # the flight recorder's rings). Overhead must stay under 5% — the
    # always-on budget from ISSUE 7, inherited by ISSUE 10.
    _phase("e2e_ingress:telemetry_off")
    os.environ["SIDDHI_TELEMETRY"] = "0"
    try:
        # identical workload at the WINNING superstep depth, so the A/B
        # isolates telemetry cost rather than dispatch-mode cost
        mgr_off, rt_off, svc_off, n_off = build_stack(best_k)
        best_off = measure(
            svc_off, rt_off,
            max(rounds, (3 * best_k + n_producers - 1) // n_producers))
        rt_off.shutdown()
        assert n_off[0] > 0
        res["telemetry_off_events_per_sec"] = round(best_off, 1)
        res["telemetry_overhead_pct"] = round(
            max(0.0, (best_off - best) / best_off) * 100.0, 2)
        _partial({"telemetry_off_events_per_sec":
                  res["telemetry_off_events_per_sec"],
                  "telemetry_overhead_pct": res["telemetry_overhead_pct"]})
    finally:
        os.environ.pop("SIDDHI_TELEMETRY", None)
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def _bench_failover_leg(reps: int = 2) -> dict:
    """ADVISORY leg of sharded_e2e (bench_compare strips it): the
    multi-host kill-one-host drill timed end to end. Two real
    `python -m siddhi_tpu.service` worker subprocesses, a FrontTier
    router in-process, one worker SIGKILLed under traffic — reports
    detection (heartbeat misses → confirmed dead), takeover (epoch
    commit + WAL-replay adoption + spool drain) and post-failover
    drain wall times, p50/p99 over `reps` drills. Wall-clock numbers
    depend on worker boot and scheduler jitter: trends, not gates."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import urllib.request

    from siddhi_tpu.parallel.front_tier import FrontTier
    from siddhi_tpu.util.faults import kill_host
    from siddhi_tpu.io import wire

    # leave the throughput phases their share of the config budget
    if time.monotonic() - T0 > CONFIG_SECONDS * 0.6:
        return {"skipped": "config time budget exhausted"}

    fo_app = """
    @app:name('FailoverBench')
    @app:shards(n='4', key='symbol')
    define stream TradeStream (symbol string, price double);
    @info(name='agg')
    from TradeStream select symbol, sum(price) as total, count() as n
    group by symbol insert into SummaryStream;
    """

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    repo = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(RNG_SEED + 11)
    detect_ms, takeover_ms, drain_ms = [], [], []
    errors = []
    for rep in range(reps):
        tmp = tempfile.mkdtemp(prefix="siddhi-bench-failover-")
        procs = []
        front = None
        try:
            ports = [free_port(), free_port()]
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env["PYTHONPATH"] = repo + os.pathsep + env.get(
                "PYTHONPATH", "")
            env.pop("SIDDHI_FAULT_SPEC", None)  # chaos stays in tests
            for p in ports:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "siddhi_tpu.service", str(p)],
                    env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL))
            for p in ports:
                boot_by = time.monotonic() + 90
                while time.monotonic() < boot_by:
                    try:
                        urllib.request.urlopen(
                            f"http://127.0.0.1:{p}/health",
                            timeout=2.0).read()
                        break
                    except OSError:
                        time.sleep(0.05)
                else:
                    raise RuntimeError(f"worker :{p} never came up")

            front = FrontTier(
                fo_app, [f"http://127.0.0.1:{p}" for p in ports],
                wal_dir=os.path.join(tmp, "wal"),
                heartbeat_interval_s=0.2, miss_threshold=2,
                max_retries=0, retry_initial_s=0.01, retry_max_s=0.02)
            front.start()
            h = front.get_input_handler("TradeStream")

            def frame(n_rows):
                ks = rng.integers(0, 64, n_rows)
                return [(f"S{int(k)}", float(v) * 0.25)
                        for k, v in zip(ks,
                                        rng.integers(1, 100, n_rows))]

            for _ in range(6):
                h.send_batch(frame(256))
            kill_host(procs[1])
            for _ in range(6):  # spools toward the dead owner
                h.send_batch(frame(256))
            by = time.monotonic() + 30
            while front.failovers_total < 1 and time.monotonic() < by:
                time.sleep(0.02)
            if not front.failover_timings:
                raise RuntimeError("takeover never completed")
            t0 = time.perf_counter()
            front.drain(timeout_s=30)
            drain_ms.append((time.perf_counter() - t0) * 1e3)
            timing = front.failover_timings[0]
            detect_ms.append(float(timing["detect_ms"] or 0.0))
            takeover_ms.append(float(timing["takeover_ms"]))
            cons = front.conservation_report()
            if not cons["conserved"]:
                raise RuntimeError(f"conservation broke: {cons}")
        except Exception as e:  # noqa: BLE001 — advisory leg never fails
            errors.append(f"rep{rep}: {type(e).__name__}: {e}")
        finally:
            if front is not None:
                try:
                    front.shutdown()
                except Exception:  # noqa: BLE001
                    pass
            for pr in procs:
                kill_host(pr)
            shutil.rmtree(tmp, ignore_errors=True)

    if not takeover_ms:
        return {"error": "; ".join(errors) or "no successful drill"}

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 1)

    out = {
        "reps": len(takeover_ms),
        "detect_ms_p50": pct(detect_ms, 50),
        "detect_ms_p99": pct(detect_ms, 99),
        "takeover_ms_p50": pct(takeover_ms, 50),
        "takeover_ms_p99": pct(takeover_ms, 99),
        "drain_ms_p50": pct(drain_ms, 50),
        "drain_ms_p99": pct(drain_ms, 99),
    }
    if errors:
        out["rep_errors"] = "; ".join(errors)
    return out


def bench_sharded_e2e() -> dict:
    """MULTICHIP config: the sharded execution plane under sustained SXF1
    frame traffic (parallel/shard_plane.py). One app text, shard counts
    swept via SIDDHI_SHARDS ∈ {1, 4, 8}: frames route by partition-key
    hash BEFORE interning, each shard runs a full replica of the
    filter → per-key running-aggregate pipeline. Two phases per count:

      parity      one deterministic single-producer feed; the canonical
                  (sorted-multiset) SHA-256 of the merged SummaryStream
                  output must be IDENTICAL across every shard count AND
                  the unsharded serial engine — prices are multiples of
                  0.25, so per-key partial sums are exact and batching
                  cannot introduce float drift
      throughput  multi-producer frame blast (the e2e_ingress shape),
                  rate = best-of-reps, plus the routing conservation
                  identity sent == Σ delivered+dropped+diverted

    scaling_x4/x8 are the honest same-host ratios vs 1 shard — on a
    single-core CPU container the replicas time-slice one core, so ~1x
    here is expected; the near-linear claim is for multi-device hosts."""
    import hashlib

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.io import wire
    from siddhi_tpu.service import SiddhiService

    eb = _resolve_e2e_batch()
    cpu = _is_cpu()
    n_producers = 2 if cpu else 4
    n_keys = 1000
    app = f"""
    @app:name('ShardedBench')
    @app:shards(n='4', key='symbol')
    @Async(buffer.size='{eb}', workers='2')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream
    select symbol, sum(price) as total, count() as n
    group by symbol
    insert into SummaryStream;
    """
    serial_app = app.replace("@app:shards(n='4', key='symbol')\n    ", "") \
                    .replace("ShardedBench", "ShardedBenchSerial")

    _phase("sharded_e2e:encode")
    rng = np.random.default_rng(RNG_SEED + 3)

    def make_body(n_rows: int, seed_frames: int):
        ks = rng.integers(0, n_keys, n_rows)
        cols = {
            "symbol": np.array([f"S{int(k)}" for k in ks], dtype=object),
            # multiples of 0.25: every per-key partial sum is exactly
            # representable, so the parity digest is bit-stable
            "price": rng.integers(1, 4000, n_rows) * 0.25,
            "volume": rng.integers(1, 1000, n_rows),
        }
        return cols

    parity_cols = make_body(8192, 4)
    bodies = []
    for _p in range(n_producers):
        per = []
        for _ in range(3):
            cols = make_body(eb, 1)
            per.append(cols)
        bodies.append(per)

    def encode_all(defn):
        plan = wire.schema_plan(defn)
        pbody = wire.encode_frames(plan, parity_cols, 8192, chunk=2048)
        tbodies = [[wire.encode_frames(plan, cols, eb) for cols in per]
                   for per in bodies]
        return pbody, tbodies

    def digest(rows) -> str:
        canon = "\n".join(repr(r) for r in sorted(rows))
        return hashlib.sha256(canon.encode()).hexdigest()

    def run_one(text, app_name, n_sh):
        if n_sh is not None:
            os.environ["SIDDHI_SHARDS"] = str(n_sh)
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(text, batch_size=eb,
                                               async_callbacks=True)
        finally:
            os.environ.pop("SIDDHI_SHARDS", None)
        svc = SiddhiService(mgr)
        rows_out = []
        collecting = [True]
        n_out = [0]

        def cb(events):
            n_out[0] += len(events)
            if collecting[0]:
                rows_out.extend(tuple(e.data) for e in events)

        rt.add_callback("SummaryStream", cb)
        rt.start()
        h = rt.get_input_handler("TradeStream")
        defn = getattr(h, "definition", None) or h.junction.definition
        pbody, tbodies = encode_all(defn)

        # phase A: deterministic parity feed (single producer). drain()
        # barriers the decoder, but @Async junctions hand rows to feeder
        # threads first — settle on the EXACT expected row count (the
        # filter's pass count is deterministic) so the digest never
        # samples mid-flight
        expected = int((parity_cols["price"] < 700.0).sum())
        svc.send_frames(app_name, "TradeStream", pbody)
        settle_by = time.monotonic() + 60.0
        while True:
            rt.drain()
            if len(rows_out) >= expected or time.monotonic() > settle_by:
                break
            time.sleep(0.02)
        assert len(rows_out) == expected, (len(rows_out), expected)
        dg = digest(rows_out)
        collecting[0] = False
        rows_out.clear()

        # phase B: multi-producer throughput
        def producer(p, n_rounds, r0):
            per = tbodies[p]
            for r in range(n_rounds):
                svc.send_frames(app_name, "TradeStream",
                                per[(r0 + r) % len(per)])

        def run_rounds(n_rounds, r0):
            ts = [threading.Thread(target=producer, args=(p, n_rounds, r0),
                                   name=f"shard-producer-{p}")
                  for p in range(n_producers)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            rt.drain()

        rounds = 2 if cpu else 4
        # warm the compile ladders off the clock with the SAME queued
        # multi-producer shape as the timed reps: back-to-back frames
        # coalesce into larger micro-batches, and every coalesced bucket
        # is a fresh executable — a single-frame warm pass would leave
        # those compiles inside the measurement window
        for _w in range(2):
            run_rounds(rounds, 0)
        best = 0.0
        r0 = 1
        for _rep in range(2):
            t0 = time.perf_counter()
            run_rounds(rounds, r0)
            best = max(best, n_producers * rounds * eb
                       / (time.perf_counter() - t0))
            r0 += rounds
        conserved = None
        if hasattr(rt, "conservation_report"):
            conserved = rt.conservation_report()["conserved"]
        rt.shutdown()
        assert n_out[0] > 0, f"{app_name}: no output — not a valid measure"
        return dg, best, conserved

    _phase("sharded_e2e:serial")
    dg_serial, _rate_serial, _ = run_one(serial_app, "ShardedBenchSerial",
                                         None)
    rates = {}
    digests = {"serial": dg_serial}
    conservation = {}
    for n_sh in (1, 4, 8):
        _phase(f"sharded_e2e:shards{n_sh}")
        dg, rate, conserved = run_one(app, "ShardedBench", n_sh)
        rates[n_sh] = rate
        digests[n_sh] = dg
        conservation[n_sh] = conserved
        _partial({f"shards_{n_sh}_events_per_sec": round(rate, 1),
                  f"shards_{n_sh}_conserved": conserved,
                  f"shards_{n_sh}_parity": dg == dg_serial})

    parity = all(d == dg_serial for d in digests.values())
    value = round(rates[4], 1)
    res = {
        "metric": "sharded_e2e_events_per_sec",
        "value": value,
        "unit": "events/sec",
        "vs_baseline": round(
            value / _baseline_for("sharded_e2e_events_per_sec"), 3),
        "shards_1": round(rates[1], 1),
        "shards_4": round(rates[4], 1),
        "shards_8": round(rates[8], 1),
        "scaling_x4": round(rates[4] / max(rates[1], 1e-9), 3),
        "scaling_x8": round(rates[8] / max(rates[1], 1e-9), 3),
        "parity": parity,
        "conserved": all(bool(c) for c in conservation.values()),
        "producers": n_producers,
    }
    _phase("sharded_e2e:failover")
    res["failover"] = _bench_failover_leg()
    _partial(res)
    assert parity, f"shard-vs-serial output digests diverged: {digests}"
    if not E2E_ONLY:
        res.update(_preflight(app))
    return res


def _fanout_app(n_queries: int) -> str:
    """N co-resident queries over ONE stream: filters with distinct
    thresholds, every 32nd a windowless group-by aggregate (sum + count per
    symbol) — all shape-polymorphic, so the optimizer fuses maximal runs
    into SharedStepGroups. Windowless aggregates rather than time windows:
    window machinery costs ~100x a filter per step and would drown the
    dispatch-bound regime this config measures in both modes."""
    lines = [
        "@app:name('FanoutBench')",
        "define stream TradeStream (symbol string, price double, "
        "volume long);",
    ]
    for i in range(n_queries):
        if i % 64 == 63:
            lines.append(
                f"@info(name='agg{i}') from TradeStream "
                f"select symbol, sum(price) as total, count() as n "
                f"group by symbol insert into AggOut{i};")
        else:
            thr = (i * 900.0) / max(n_queries, 1)
            lines.append(
                f"@info(name='filt{i}') from TradeStream[price > {thr:.1f}] "
                f"select symbol, price insert into FiltOut{i};")
    return "\n".join(lines)


def bench_fanout() -> dict:
    """HEADLINE config: multi-tenant fan-out — N ∈ {1, 16, 64, 256}
    filter/aggregate queries over one stream fed via SXF1 binary frames
    through the service surface, with the multi-query optimizer ON vs OFF.
    Reports events/s and the XLA compile count at each N: with the optimizer
    the compile count stays O(fused groups) while throughput holds; without
    it both scale linearly with N (the paper's multi-tenant cost problem,
    ROADMAP open item #1). Also records e2e_rows_events_per_sec — the
    row-at-a-time compatibility tier's measured number (VERDICT item 10)."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.io import wire
    from siddhi_tpu.service import SiddhiService

    cpu = _is_cpu()
    # dispatch-bound regime ON PURPOSE: small batches + many queries is
    # where per-query dispatch dominates and fusion pays. At large batches
    # the run is compute-bound and both modes converge on the same XLA work.
    bb = int(os.environ.get("SIDDHI_FANOUT_BATCH", 0)) or 128
    # group_capacity bounds the per-aggregate key table; the repo default
    # (1 << 20 slots) makes each group-by step carry million-entry state —
    # pure overhead at 100 distinct keys.
    gc = int(os.environ.get("SIDDHI_FANOUT_GROUP_CAPACITY", 0)) or 4096
    n_keys = 100
    rng = np.random.default_rng(RNG_SEED + 3)
    res: dict = {"metric": "fanout256_events_per_sec", "unit": "events/sec",
                 "batch": bb, "group_capacity": gc}
    deadline = time.monotonic() + max(CONFIG_SECONDS - 30.0, 60.0)

    def run_mode(n_queries: int, optimize: bool, rounds: int):
        app = _fanout_app(n_queries)
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(app, batch_size=bb,
                                           group_capacity=gc,
                                           optimize=optimize)
        svc = SiddhiService(mgr)
        n_out = [0]
        rt.add_callback("FiltOut0", lambda blk: n_out.__setitem__(
            0, n_out[0] + blk.count), columnar=True)
        rt.start()
        rt.warmup((bb,))
        plan = wire.schema_plan(rt.junctions["TradeStream"].definition)
        bodies = []
        for _ in range(3):
            ks = rng.integers(1, n_keys + 1, bb)
            cols = {
                "symbol": np.array([f"S{int(k)}" for k in ks], dtype=object),
                "price": rng.uniform(1.0, 1000.0, bb),
                "volume": rng.integers(1, 1000, bb),
            }
            bodies.append(wire.encode_frames(plan, cols, bb))

        def run_rounds(k: int, r0: int) -> None:
            for r in range(k):
                svc.send_frames("FanoutBench", "TradeStream",
                                bodies[(r0 + r) % len(bodies)])
            rt.drain()

        run_rounds(2, 0)  # residual compiles (partial shapes) out of measure
        best, r0 = 0.0, 2
        for _rep in range(3):
            t0 = time.perf_counter()
            run_rounds(rounds, r0)
            elapsed = time.perf_counter() - t0
            r0 += rounds
            best = max(best, rounds * bb / elapsed)
        rep = rt.statistics_report()
        compiles = sum(rep["compiles"].values())
        opt_section = rep.get("optimizer", {})
        rt.shutdown()
        assert n_out[0] > 0, "fanout produced no output — not a valid measure"
        return best, compiles, opt_section

    # small-batch regime: enough rounds that each timed rep spans >100 ms
    # even in the fast (fused) mode, or rep-to-rep jitter dominates
    rounds = 24 if cpu else 32
    fanout_ns = (1, 16, 64, 256)
    for n in fanout_ns:
        if time.monotonic() > deadline and n > 1:
            _partial({f"fanout{n}_skipped": "config budget exhausted"})
            continue
        _phase(f"fanout:{n}q:optimizer_on")
        ev_on, comp_on, opt = run_mode(n, True, rounds)
        _partial({f"fanout{n}_on_events_per_sec": round(ev_on, 1),
                  f"fanout{n}_on_compiles": comp_on,
                  f"fanout{n}_groups": opt.get("groups", 0),
                  f"fanout{n}_queries_fused": opt.get("queries_fused", 0),
                  f"fanout{n}_compiles_avoided":
                      opt.get("compiles_avoided", 0)})
        res.update(PARTIAL)
        if time.monotonic() > deadline and n > 1:
            _partial({f"fanout{n}_off_skipped": "config budget exhausted"})
            continue
        _phase(f"fanout:{n}q:optimizer_off")
        ev_off, comp_off, _ = run_mode(n, False, rounds)
        _partial({f"fanout{n}_off_events_per_sec": round(ev_off, 1),
                  f"fanout{n}_off_compiles": comp_off,
                  f"fanout{n}_speedup": round(ev_on / max(ev_off, 1e-9), 2)})
        res.update(PARTIAL)

    # headline value: optimizer-on events/s at the largest N that completed
    for n in reversed(fanout_ns):
        v = res.get(f"fanout{n}_on_events_per_sec")
        if v is not None:
            res["value"] = v
            res["headline_n_queries"] = n
            break
    res["vs_baseline"] = round(
        res.get("value", 0.0) / _baseline_for("fanout256_events_per_sec"), 3)

    # rows-path compatibility tier: the same public path fed with per-row
    # Python tuples + per-Event callbacks (VERDICT item 10's missing number)
    _phase("fanout:rows_path")
    eb = _resolve_e2e_batch()
    app1 = _fanout_app(1)
    rt3 = SiddhiManager().create_siddhi_app_runtime(
        app1, batch_size=eb, async_callbacks=True)
    rows = _trade_rows(4, n_keys, price_hi=1000.0, n=eb)
    h3 = rt3.get_input_handler("TradeStream")

    def feed_rows(r):
        h3.send_batch(rows[r % len(rows)])
        rt3.flush()

    res["e2e_rows_events_per_sec"] = round(
        _measure_e2e(rt3, "FiltOut0", feed_rows, eb,
                     columnar=False, rounds=4), 1)
    _partial({"e2e_rows_events_per_sec": res["e2e_rows_events_per_sec"]})
    if not E2E_ONLY:
        res.update(_preflight(_fanout_app(16)))
    return res


def _churn_query(i: int) -> str:
    thr = (i * 900.0) / 1024.0
    return (f"@info(name='cq{i}') from TradeStream[price > {thr:.1f}] "
            f"select symbol, price insert into ChurnOut{i};")


def _churn_app(n_queries: int) -> str:
    lines = [
        "@app:name('ChurnBench')",
        "define stream TradeStream (symbol string, price double, "
        "volume long);",
    ]
    for i in range(n_queries):
        lines.append(_churn_query(i))
    return "\n".join(lines)


def bench_churn() -> dict:
    """Churn drill: Poisson attach/detach against a live fused fleet under
    sustained SXF1 traffic (the multi-tenant churn proof). Queries splice
    into/out of live SharedStepGroups with ONE retrace — no drain, no
    stop-the-world redeploy — so the bar is threefold: attach deploy
    latency p50/p99 (parse → spliced → warmed), the throughput of the
    block of rounds IMMEDIATELY after each splice vs a settled block at
    the same membership (churn_splice_throughput_ratio, advisory floor
    0.9 — no cliff at splice points; pairing at equal membership keeps
    deliberate fleet growth from masquerading as one), and bit-identical
    output from a sampled spliced-in query vs a from-scratch
    single-query build fed identical frames.
    SIDDHI_STATE_BUDGET is set for the drill so EVERY attach is priced by
    the per-splice SL501 admission gate (one deliberately oversized attach
    proves refusal), and the final fleet must sit under the budget.
    SIDDHI_CHURN_QUERIES scales the drill (default 1000 on accelerators,
    64 on CPU where each retrace is an XLA:CPU compile)."""
    from siddhi_tpu import SiddhiManager, compiler
    from siddhi_tpu.analysis.cost import compute_cost
    from siddhi_tpu.errors import SiddhiAppCreationError
    from siddhi_tpu.io import wire
    from siddhi_tpu.service import SiddhiService

    cpu = _is_cpu()
    total_q = int(os.environ.get("SIDDHI_CHURN_QUERIES", 0)) or \
        (64 if cpu else 1000)
    base_n = max(2, min(64, total_q // 4))
    bb = int(os.environ.get("SIDDHI_FANOUT_BATCH", 0)) or 128
    n_keys = 100
    rng = np.random.default_rng(RNG_SEED + 5)
    res: dict = {"metric": "churn_sustained_events_per_sec",
                 "unit": "events/sec", "batch": bb,
                 "queries_target": total_q, "queries_base": base_n}
    deadline = time.monotonic() + max(CONFIG_SECONDS - 30.0, 60.0)

    # price the FULL drill fleet once and set the budget with headroom:
    # admission control runs on every attach without starving the churn
    budget = int(compute_cost(compiler.parse(_churn_app(total_q)),
                              batch_size=bb).state_bytes * 1.5) + 1
    os.environ["SIDDHI_STATE_BUDGET"] = str(budget)

    _phase("churn:build")
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(_churn_app(base_n), batch_size=bb,
                                       optimize=True)
    svc = SiddhiService(mgr)
    rt.start()
    rt.warmup((bb,))
    plan = wire.schema_plan(rt.junctions["TradeStream"].definition)
    bodies = []
    for _ in range(3):
        ks = rng.integers(1, n_keys + 1, bb)
        cols = {
            "symbol": np.array([f"S{int(k)}" for k in ks], dtype=object),
            "price": rng.uniform(1.0, 1000.0, bb),
            "volume": rng.integers(1, 1000, bb),
        }
        bodies.append(wire.encode_frames(plan, cols, bb))
    r = [0]

    def send_round() -> None:
        svc.send_frames("ChurnBench", "TradeStream",
                        bodies[r[0] % len(bodies)])
        r[0] += 1

    # no-churn baseline: median over blocks of the SAME shape the drill
    # times at each splice point (BLOCK rounds + one drain), so the ratio
    # compares like with like; the block is wide enough that the one-time
    # post-attach first-touch (~1 ms of lazy output-path init) reads as
    # the noise it is, not as a sustained cliff
    BLOCK = 24

    def block_rate() -> float:
        t0 = time.perf_counter()
        for _ in range(BLOCK):
            send_round()
        rt.drain()
        return BLOCK * bb / (time.perf_counter() - t0)

    _phase("churn:baseline")
    for _ in range(4):
        send_round()
    rt.drain()
    base_rate = float(np.median([block_rate() for _ in range(3)]))
    _partial({"churn_no_churn_events_per_sec": round(base_rate, 1)})

    # the drill: Poisson-paced attach/detach under continuous traffic
    _phase("churn:drill")
    deploy_ms: list = []
    post_splice_rates: list = []
    attaches = detaches = refused = 0
    active = list(range(base_n))
    next_i = base_n
    ev_total = 0
    churn_t0 = time.perf_counter()
    while next_i < total_q and time.monotonic() < deadline:
        for _ in range(1 + int(rng.poisson(1.0))):
            send_round()
            ev_total += bb
        rt.drain()
        if len(active) > base_n and rng.random() < 0.35:
            victim = active.pop(int(rng.integers(len(active))))
            mgr.detach_query("ChurnBench", f"cq{victim}")
            detaches += 1
        else:
            try:
                out = mgr.attach_query("ChurnBench", _churn_query(next_i))
            except SiddhiAppCreationError:
                refused += 1
                next_i += 1
                continue
            deploy_ms.append(out["deploy_ms"])
            attaches += 1
            active.append(next_i)
            next_i += 1
            # no-cliff check AT the splice point: the block of rounds
            # immediately after the splice vs a settled block right after
            # it — SAME membership, so fleet growth (more queries = more
            # work per batch, by design) doesn't masquerade as a cliff
            at_splice = block_rate()
            settled = block_rate()
            post_splice_rates.append(at_splice / max(settled, 1e-9))
            ev_total += 2 * BLOCK * bb
        if (attaches + detaches) % 32 == 0 and deploy_ms:
            _partial({"churn_attaches": attaches,
                      "churn_detaches": detaches,
                      "churn_deploy_p99_ms": round(
                          float(np.percentile(deploy_ms, 99)), 2)})
    churn_elapsed = time.perf_counter() - churn_t0

    # one deliberately oversized attach: the per-splice SL501 gate must
    # refuse it (splices never queue) without disturbing the fleet
    _phase("churn:admission")
    try:
        mgr.attach_query(
            "ChurnBench",
            "@info(name='cqbig') from TradeStream#window.length(1048576) "
            "select symbol, sum(price) as t insert into BigOut;")
        sl501_ok = 0.0
    except SiddhiAppCreationError:
        refused += 1
        sl501_ok = 1.0
    predicted = int(rt.cost_report.get("predicted_state_bytes", 0))
    assert predicted <= budget, \
        f"fleet {predicted} over SIDDHI_STATE_BUDGET {budget}"

    # oracle digest: the most recently spliced-in survivor must match a
    # from-scratch single-query build bit-for-bit on identical frames
    _phase("churn:oracle")
    sample = active[-1]
    got_live: list = []
    rt.add_callback(f"ChurnOut{sample}", lambda evs: got_live.extend(
        tuple(e.data) for e in evs))
    for _ in range(4):
        send_round()
    rt.drain()
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(
        "@app:name('ChurnBench')\n"
        "define stream TradeStream (symbol string, price double, "
        "volume long);\n" + _churn_query(sample),
        batch_size=bb, optimize=False)
    got_scratch: list = []
    rt2.add_callback(f"ChurnOut{sample}", lambda evs: got_scratch.extend(
        tuple(e.data) for e in evs))
    rt2.start()
    svc2 = SiddhiService(m2)
    for i in range(r[0] - 4, r[0]):
        svc2.send_frames("ChurnBench", "TradeStream",
                         bodies[i % len(bodies)])
    rt2.drain()
    assert got_live and got_live == got_scratch, \
        "spliced-in query diverged from its from-scratch build"
    rt2.shutdown()

    stats = rt.statistics_report()
    opt = rt.optimizer_report or {}
    rt.shutdown()
    os.environ.pop("SIDDHI_STATE_BUDGET", None)
    ratio = (float(np.median(post_splice_rates))
             if post_splice_rates else 0.0)
    res.update({
        "value": round(ev_total / churn_elapsed, 1),
        "churn_no_churn_events_per_sec": round(base_rate, 1),
        "churn_splice_throughput_ratio": round(ratio, 3),
        "churn_deploy_p50_ms": round(
            float(np.percentile(deploy_ms, 50)), 2) if deploy_ms else None,
        "churn_deploy_p99_ms": round(
            float(np.percentile(deploy_ms, 99)), 2) if deploy_ms else None,
        "churn_attaches": attaches,
        "churn_detaches": detaches,
        "churn_sl501_refused": refused,
        "churn_sl501_gate_ok": sl501_ok,
        "churn_oracle_ok": 1.0,
        "churn_queries_final": len(active),
        "churn_groups": opt.get("groups", 0),
        "churn_splices": (stats.get("splices") or {}).get("counts", {}),
        "churn_state_budget_bytes": budget,
        "churn_predicted_state_bytes": predicted,
    })
    _partial({k: res[k] for k in res if k.startswith("churn_")})
    res["vs_baseline"] = round(
        res["value"] / _baseline_for("churn_sustained_events_per_sec"), 3)
    if not E2E_ONLY:
        res.update(_preflight(_churn_app(16)))
    return res


def bench_hang() -> dict:
    """HIDDEN config (`python bench.py _hang`): deliberately wedges before
    importing anything heavy AND swallows the in-process alarm — the
    watchdog unit test proves the PARENT deadline bounds even a config the
    child-side alarm cannot stop, while the partials still yield a JSON
    line."""
    _partial({"metric": "hang_test", "stage_one": 1.0})
    _phase("_hang:sleeping")
    while True:
        try:
            time.sleep(3600)
        except BenchTimeout:
            pass  # simulate a hang no Python-level bound can interrupt


CONFIGS = {
    "filter": bench_filter,
    "distinct": bench_distinct,
    "pattern": bench_pattern,
    "join": bench_join,
    "overload": bench_overload,  # bounded ingress under 10x overload
    "disorder": bench_disorder,  # out-of-order ingress through the
    # @app:eventTime gate: gated rate + exact late-diversion counts
    "upgrade": bench_upgrade,  # blue-green hot-swap under live traffic
    "groupby": bench_groupby,
    "e2e_ingress": bench_e2e_ingress,  # wire→pipeline→device rate
    "sharded_e2e": bench_sharded_e2e,  # partition-key shard plane: parity,
    # conservation, and same-host scaling at shards {1, 4, 8}
    "churn": bench_churn,  # Poisson attach/detach splice drill: deploy
    # latency p50/p99, no-cliff ratio at splice points, SL501 per splice
    "fanout": bench_fanout,  # HEADLINE: keep last — drivers that parse only
    # the final line track the multi-tenant shared-execution rate
}
#: not part of the default run; reachable by explicit name only
HIDDEN_CONFIGS = {"_hang": bench_hang}


def _run_config_subprocess(argv, env=None, timeout: float = 900.0):
    """Run one config in a fresh interpreter under a HARD parent deadline.
    The child's stdout is streamed live: `#partial {json}` checkpoint lines
    accumulate so a killed child still yields numbers for every sub-metric
    that finished (merged under "partial": true). stderr (heartbeats)
    passes straight through to our stderr."""
    import subprocess
    t0 = time.monotonic()
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE, stderr=None,
                            text=True, env=env)
    partial: dict = {}
    final: list = []

    def _reader():
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("#partial "):
                try:
                    partial.update(json.loads(line[len("#partial "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("{"):
                final.append(line)

    rd = threading.Thread(target=_reader, daemon=True)
    rd.start()
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover — kill -9'd
            pass
        rd.join(timeout=5)
        elapsed = time.monotonic() - t0
        return {**partial, "partial": True,
                "error": f"timeout after {elapsed:.0f}s"}
    rd.join(timeout=10)
    if not final:
        if partial:  # child died mid-run (alarm/OOM) but checkpointed
            return {**partial, "partial": True,
                    "error": f"config exited rc={proc.returncode} "
                             "before the final line"}
        return {"error": f"no output (rc={proc.returncode})"}
    try:
        return json.loads(final[-1])
    except json.JSONDecodeError:
        return {"error": final[-1][-400:]}


def _run_child(name: str) -> None:
    """Child mode: one config, best-effort SIGALRM + heartbeat, partial
    JSON on expiry. The parent's kill is the hard bound; the alarm lets a
    Python-visible stall report its own partials first."""
    fn = {**CONFIGS, **HIDDEN_CONFIGS}[name]
    _arm_child_watchdog(max(CONFIG_SECONDS - 5.0, 1.0))
    try:
        if name != "_hang":  # _hang must stay import-free
            _resolve_e2e_batch()
            import jax
            # the parent skips its colocated-CPU pass when this child
            # already ran on CPU (same backend twice = wasted budget)
            _partial({"backend": jax.default_backend()})
        res = fn()
        res.setdefault("backend", PARTIAL.get("backend"))
    except BenchTimeout as e:
        res = {**PARTIAL, "partial": True, "error": str(e)}
        res.setdefault("metric", name)
    print(json.dumps(res), flush=True)


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    known = {**CONFIGS, **HIDDEN_CONFIGS}
    unknown = [n for n in args if n not in known]
    if unknown:
        sys.exit(f"unknown config(s) {unknown}; choose from {list(CONFIGS)}")
    names = args or list(CONFIGS)
    # child mode is EXPLICIT (--child / --e2e-only): a bare single-config
    # invocation still gets the parent-side watchdog
    if E2E_ONLY or "--child" in sys.argv:
        if E2E_ONLY and os.environ.get("SIDDHI_BENCH_CPU"):
            # co-located variant: same engine, CPU backend in-process — no
            # tunnel between controller and device
            from siddhi_tpu.util.platform import force_cpu_platform
            force_cpu_platform(1)
        _run_child(names[0])
        return
    # one subprocess per config: earlier configs' runtimes pin device buffers
    # (1M-key tables, 100k rings) and degrade later configs measurably when
    # sharing a process. Per-config deadline = the config's FAIR SHARE of
    # the remaining outer budget (capped at CONFIG_SECONDS): one slow early
    # config can no longer eat the tail configs' slices — the run always
    # reaches the headline (last) config and emits its final JSON line
    # inside the driver's wall limit. Unused share rolls forward.
    for i, name in enumerate(names):
        remaining = MAX_SECONDS - (time.monotonic() - T0)
        left = len(names) - i
        if remaining < 20:
            print(json.dumps({
                "metric": name, "error": "skipped: --max-seconds budget "
                f"exhausted ({MAX_SECONDS:.0f}s)"}), flush=True)
            continue
        budget = min(CONFIG_SECONDS, max(remaining / left, 20.0), remaining)
        print(f"[bench] t={time.monotonic() - T0:.0f}s config={name} "
              f"({i + 1}/{len(names)}) budget={budget:.0f}s "
              f"(fair share of {remaining:.0f}s over {left})",
              file=sys.stderr, flush=True)
        res = _run_config_subprocess(
            [sys.executable, __file__, name, "--child",
             f"--config-seconds={budget:.0f}"],
            timeout=budget)
        res.setdefault("metric", name)
        if "error" in res and not res.get("partial"):
            print(json.dumps(res), flush=True)
            continue
        # co-located CPU e2e (VERDICT r3 item 1: separate topology from
        # engine): same public path, CPU backend, fresh subprocess. Skipped
        # when the primary child already ran on CPU (it IS the co-located
        # number), and bounded so the configs still queued keep a floor of
        # ~45 s each of the remaining budget.
        remaining = MAX_SECONDS - (time.monotonic() - T0)
        reserve = 45.0 * (len(names) - i - 1)
        if (remaining - reserve > 30 and "error" not in res
                and res.get("backend") != "cpu"):
            cpu_budget = min(90.0, CONFIG_SECONDS, remaining - reserve)
            cpu_env = dict(os.environ,
                           JAX_PLATFORMS="cpu", SIDDHI_BENCH_CPU="1")
            cpu = _run_config_subprocess(
                [sys.executable, __file__, name, "--e2e-only",
                 f"--config-seconds={cpu_budget:.0f}"],
                env=cpu_env, timeout=cpu_budget)
            if "e2e_events_per_sec" in cpu:
                res["e2e_colocated_events_per_sec"] = cpu["e2e_events_per_sec"]
            if "p99_autoflush_latency_ms" in cpu:
                res["p99_autoflush_latency_ms_colocated"] = \
                    cpu["p99_autoflush_latency_ms"]
        res.pop("backend", None)  # routing detail, not a result
        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
