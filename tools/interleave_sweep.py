#!/usr/bin/env python
"""Deterministic interleaving sweep: seeded schedule fuzzing over the
engine's three racy-by-construction flows, with lockdep certification.

For each seed, `util.locks` arms its seeded preemption points (the
perturbation schedule is a pure function of seed × lock name × per-thread
acquisition counter — a failing seed replays the same pressure pattern)
and three scenarios run against conservation oracles:

  ingress   4 producer threads hammer one @Async stream; every event must
            arrive exactly once, per-producer FIFO intact.
  upgrade   a producer streams through a blue-green hot swap; every event
            is processed by exactly one version — no loss, no dupes.
  shutdown  SLO ticks, flight-recorder triggers, and statistics_report()
            race shutdown(); nothing may deadlock or raise.

All scenarios run with SIDDHI_LOCK_CHECKS semantics on (the sweep enables
tracking in-process), so the run double-checks the acceptance invariant:
ZERO lock-order cycles and ZERO held-across-blocking hazards on the real
runtime, under schedule pressure.

    python tools/interleave_sweep.py [--seeds 16] [--base 1000] [--json]

Exit codes: 0 = every seed clean, 1 = an oracle or lockdep finding failed.
One process, one jax import: a 16-seed sweep stays CI-sized.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from siddhi_tpu import SiddhiManager  # noqa: E402
from siddhi_tpu.state.persistence import InMemoryPersistenceStore  # noqa: E402
from siddhi_tpu.util import locks  # noqa: E402


# --------------------------------------------------------------------------
# scenarios — each returns None on success, a failure string otherwise
# --------------------------------------------------------------------------

def scenario_ingress(seed: int):
    """4 producers × N events through one @Async junction: conservation +
    per-producer FIFO (the MPSC ring + feeder + controller path)."""
    n, producers = 150, 4
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@Async(buffer.size='32')\n"
        "define stream S (producer long, seq long);\n"
        "@info(name='q') from S select producer, seq insert into Out;")
    got, gl = [], threading.Lock()

    def cb(ts, ins, removed):
        with gl:
            got.extend(tuple(e.data) for e in ins or [])

    rt.add_query_callback("q", cb)
    rt.start()
    h = rt.get_input_handler("S")

    def produce(pid):
        for s in range(n):
            h.send((pid, s))

    threads = [threading.Thread(target=produce, args=(p,))
               for p in range(producers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if any(t.is_alive() for t in threads):
        return "producer thread wedged"
    rt.flush()
    rt.shutdown()
    if len(got) != n * producers:
        return f"conservation: {len(got)} != {n * producers}"
    for p in range(producers):
        seqs = [s for pid, s in got if pid == p]
        if seqs != list(range(n)):
            return f"producer {p} FIFO broken"
    return None


def scenario_upgrade(seed: int):
    """Producer streams through a blue-green swap; every event lands in
    exactly one version (core/upgrade.py conservation invariant)."""
    n = 400
    v1 = ("@app:name('Sweep')\n"
          "define stream S (k string, v long);\n"
          "@info(name='q') from S select k, v insert into Out;")
    v2 = v1 + "\n@info(name='extra') from S select v insert into Copy;"
    mgr = SiddhiManager()
    mgr.set_persistence_store(InMemoryPersistenceStore())
    rt1 = mgr.create_siddhi_app_runtime(v1, batch_size=8)
    seen, gl = [], threading.Lock()
    rt1.add_callback("Out", lambda evs: seen.extend(e.data[1] for e in evs))
    rt1.start()
    h = rt1.get_input_handler("S")
    started = threading.Event()

    def produce():
        for i in range(n):
            h.send((f"k{i % 5}", i), timestamp=1_000 + i)
            if i == n // 8:
                started.set()
            if i % 32 == 0:
                mgr.runtimes["Sweep"].flush()

    t = threading.Thread(target=produce)
    t.start()
    started.wait(timeout=30)
    summary = mgr.upgrade(v2)
    t.join(timeout=60)
    if t.is_alive():
        return "producer wedged across the swap"
    if summary["status"] != "swapped":
        return f"upgrade not swapped: {summary['status']}"
    rt2 = mgr.runtimes["Sweep"]
    rt2.drain()
    rt2.shutdown()
    missing = len([x for x in range(n) if x not in set(seen)])
    if sorted(seen) != list(range(n)):
        return (f"conservation across swap: {len(seen)} events, "
                f"{missing} missing")
    return None


def scenario_shutdown(seed: int):
    """SLO ticks + recorder triggers + statistics_report racing
    shutdown(): the telemetry locks vs. teardown."""
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:name('Tick')\n"
        "@app:slo(stream='S', p99.ms='50', min.rate='1')\n"
        "define stream S (k string, v long);\n"
        "@info(name='q') from S select k, v insert into Out;")
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(64):
        h.send((f"k{i % 3}", i), timestamp=1_000 + i)
    rt.flush()

    stop = threading.Event()
    errors: list = []

    def churn():
        i = 0
        while not stop.is_set():
            try:
                if rt.slo_engine is not None:
                    rt.slo_engine.tick(now=2_000.0 + i)
                rt.ctx.recorder.trigger("sweep", reason=f"seed {seed}/{i}")
                rt.statistics_report()
            except Exception as e:  # noqa: BLE001 — the oracle
                errors.append(repr(e))
                return
            i += 1

    t = threading.Thread(target=churn)
    t.start()
    time.sleep(0.05)
    done = threading.Event()

    def teardown():
        rt.shutdown()
        done.set()

    td = threading.Thread(target=teardown)
    td.start()
    if not done.wait(timeout=60):
        stop.set()
        return "shutdown wedged against telemetry churn"
    stop.set()
    t.join(timeout=30)
    td.join(timeout=5)
    if errors:
        return f"telemetry churn raised: {errors[0]}"
    return None


SCENARIOS = (("ingress", scenario_ingress),
             ("upgrade", scenario_upgrade),
             ("shutdown", scenario_shutdown))


def run_seed(seed: int) -> dict:
    locks.enable_checks(True)
    locks.set_schedule_fuzz(seed)
    locks.lockdep_reset()
    out: dict = {"seed": seed, "scenarios": {}, "ok": True}
    for name, fn in SCENARIOS:
        t0 = time.monotonic()
        try:
            failure = fn(seed)
        except Exception as e:  # noqa: BLE001 — a crash is a failure too
            failure = f"raised {e!r}"
        out["scenarios"][name] = {
            "failure": failure,
            "seconds": round(time.monotonic() - t0, 2),
        }
        if failure:
            out["ok"] = False
    rep = locks.lockdep_report()
    out["lockdep"] = {"cycles": rep["cycles"], "hazards": rep["hazards"],
                      "edges": len(rep["edges"]), "locks": len(rep["locks"])}
    if rep["cycles"] or rep["hazards"]:
        out["ok"] = False
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seeds", type=int, default=16)
    ap.add_argument("--base", type=int, default=1000)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    results = []
    failed = 0
    for k in range(args.seeds):
        seed = args.base + k
        r = run_seed(seed)
        results.append(r)
        if not r["ok"]:
            failed += 1
        if not args.as_json:
            secs = sum(s["seconds"] for s in r["scenarios"].values())
            detail = "; ".join(
                f"{n}: {s['failure']}" for n, s in r["scenarios"].items()
                if s["failure"])
            ld = r["lockdep"]
            if ld["cycles"] or ld["hazards"]:
                detail += (f" lockdep: {len(ld['cycles'])} cycle(s) "
                           f"{len(ld['hazards'])} hazard(s)")
            print(f"seed {seed}: {'FAIL ' + detail if not r['ok'] else 'ok'}"
                  f" ({secs:.1f}s, {ld['edges']} edges)")
            sys.stdout.flush()
    # findings detail at the end so a failing CI log leads with them
    for r in results:
        for c in r["lockdep"]["cycles"]:
            print(f"seed {r['seed']} CYCLE {' -> '.join(c['cycle'])}\n"
                  f"{c['this_site']}", file=sys.stderr)
        for h in r["lockdep"]["hazards"]:
            print(f"seed {r['seed']} HAZARD {h['held']} held across "
                  f"{h['blocking']}\n{h['site']}", file=sys.stderr)
    if args.as_json:
        print(json.dumps({"results": results, "failed": failed}, indent=2))
    else:
        print(f"interleave sweep: {args.seeds - failed}/{args.seeds} "
              f"seeds clean")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
