#!/usr/bin/env python
"""CI smoke: scrape /metrics DURING live e2e traffic and validate it.

Boots the REST service in-process on an ephemeral port, deploys an
@Async-pipelined app, then runs producer threads pushing SXF1 binary
frames while a scraper thread hits GET /metrics concurrently — the
scrape path must answer while the ingress pipeline, controller, and
deploy lock are all busy. Every scrape body must

  * pass telemetry.prometheus.validate_exposition (zero errors), and
  * contain a TYPE line for every ALWAYS_ON_FAMILIES entry,

and the final scrape must additionally show real traffic (events_total
matching what was sent, per-query latency histogram populated). Exits
non-zero with a diagnostic on any violation.

A second chaos stage then deploys an app with a declared SLO and a
1-failure breaker, poisons its query through the fault-injection
harness, and asserts the observability loop closes end to end: the
breaker trip makes the flight recorder freeze a diagnostic bundle,
GET /slo serves the objective report, and `python -m siddhi_tpu.doctor`
exits 3 (degraded) naming the open breaker.

Usage:  python tools/metrics_smoke.py [--rows 20000] [--producers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # run from anywhere without installing

import numpy as np

APP = """@app:name('smoke')
@Async(buffer.size='2048', workers='2')
define stream TradeStream (symbol string, price double, volume long);
@info(name='q')
from TradeStream[price >= 0.0]
select symbol, price, volume
insert into OutStream;
"""


def _get(base: str, path: str) -> tuple[int, str, str]:
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return (resp.status, resp.headers.get("Content-Type", ""),
                resp.read().decode())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=20_000,
                    help="rows per producer")
    ap.add_argument("--producers", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=2048)
    args = ap.parse_args()

    from siddhi_tpu.io import wire
    from siddhi_tpu.service import SiddhiService
    from siddhi_tpu.telemetry.prometheus import (ALWAYS_ON_FAMILIES,
                                                 validate_exposition)

    svc = SiddhiService()
    httpd = svc.make_server(port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    failures: list[str] = []

    def check_scrape(text: str, ctype: str, tag: str) -> None:
        if not ctype.startswith("text/plain"):
            failures.append(f"{tag}: bad content-type {ctype!r}")
        for err in validate_exposition(text):
            failures.append(f"{tag}: {err}")
        for fam in ALWAYS_ON_FAMILIES:
            if f"# TYPE {fam} " not in text:
                failures.append(f"{tag}: missing always-on family {fam}")

    # 1. pre-deploy: a fresh service must already expose its schema
    status, ctype, text = _get(base, "/metrics")
    assert status == 200, status
    check_scrape(text, ctype, "pre-deploy scrape")

    svc.deploy(APP)
    rt = svc.manager.runtimes["smoke"]
    handler = rt.get_input_handler("TradeStream")
    plan = wire.schema_plan(handler.junction.definition)

    # 2. concurrent producers (binary SXF1 frames, the zero-copy path)
    def produce(seed: int) -> None:
        rng = np.random.default_rng(seed)
        cols = {
            "symbol": np.array([f"S{i % 31}" for i in range(args.rows)],
                               dtype=object),
            "price": rng.uniform(1.0, 900.0, args.rows),
            "volume": rng.integers(1, 1000, args.rows,
                                   dtype=np.int64),
        }
        body = wire.encode_frames(plan, cols, args.rows,
                                  ts=np.arange(1, args.rows + 1,
                                               dtype=np.int64),
                                  chunk=args.chunk)
        req = urllib.request.Request(
            f"{base}/siddhi-apps/smoke/streams/TradeStream", data=body,
            headers={"Content-Type": "application/x-siddhi-frames"},
            method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            got = json.loads(resp.read())
            assert got["accepted"] == args.rows, got

    producers = [threading.Thread(target=produce, args=(100 + i,))
                 for i in range(args.producers)]
    stop = threading.Event()
    mid_scrapes = []

    def scrape_loop() -> None:
        while not stop.is_set():
            try:
                _, ctype, text = _get(base, "/metrics")
                mid_scrapes.append((ctype, text))
            except Exception as e:  # noqa: BLE001 — record, keep scraping
                failures.append(f"mid-traffic scrape raised: {e}")
            stop.wait(0.05)

    scraper = threading.Thread(target=scrape_loop)
    scraper.start()
    for p in producers:
        p.start()
    for p in producers:
        p.join()
    rt.drain()
    stop.set()
    scraper.join()

    if not mid_scrapes:
        failures.append("scraper got zero bodies during traffic")
    for i, (ctype, text) in enumerate(mid_scrapes):
        check_scrape(text, ctype, f"mid-traffic scrape #{i}")

    # 3. final scrape reflects the traffic exactly
    _, ctype, text = _get(base, "/metrics")
    check_scrape(text, ctype, "final scrape")
    total = args.rows * args.producers
    want = f'siddhi_events_total{{app="smoke",stream="TradeStream"}} {total}'
    if want not in text:
        got = [ln for ln in text.splitlines()
               if ln.startswith("siddhi_events_total")]
        failures.append(f"final scrape: expected {want!r}, got {got}")
    if ('siddhi_query_latency_seconds_count{app="smoke",query="q"}'
            not in text):
        failures.append("final scrape: per-query latency histogram missing")

    # probes stayed lock-free and honest throughout
    status, _, ready = _get(base, "/ready")
    if status != 200 or not json.loads(ready)["ready"]:
        failures.append(f"/ready degraded after traffic: {ready}")

    # 4. chaos: breaker trip -> flight-recorder bundle -> doctor verdict
    import subprocess
    import tempfile
    from siddhi_tpu.util.faults import apply_fault_spec
    diag = tempfile.mkdtemp(prefix="smoke-diag-")
    os.environ["SIDDHI_DIAG_DIR"] = diag
    svc.deploy("@app:name('chaos')\n"
               "@app:slo(stream='S', p99.ms='50', min.samples='3')\n"
               "define stream S (v long);\n"
               "@info(name='q') @breaker(threshold='1')\n"
               "from S select v insert into Out;\n")
    rt2 = svc.manager.runtimes["chaos"]
    apply_fault_spec(rt2, "query:p=1.0,exc=error,seed=7")
    h2 = rt2.get_input_handler("S")
    for i in range(8):
        h2.send((i,))
    rt2.flush()
    brk = rt2.statistics_report().get("breakers", {}).get("q", {})
    if brk.get("state") != "open":
        failures.append(f"chaos: breaker did not open: {brk}")
    rec_rep = rt2.ctx.recorder.report()
    if rec_rep["bundles_written"] < 1 or not rec_rep["last_bundle"]:
        failures.append(f"chaos: no diagnostic bundle written: {rec_rep}")
    else:
        doc = subprocess.run(
            [sys.executable, "-m", "siddhi_tpu.doctor",
             rec_rep["last_bundle"]],
            capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))})
        if doc.returncode != 3:
            failures.append(
                f"chaos: doctor exit {doc.returncode} != 3 (degraded); "
                f"stdout: {doc.stdout!r} stderr: {doc.stderr!r}")
        if "circuit breaker" not in doc.stdout:
            failures.append(
                f"chaos: doctor did not name the breaker: {doc.stdout!r}")
    status, _, slo_body = _get(base, "/slo")
    try:
        slo = json.loads(slo_body)
        if "stream:S:p99.ms" not in (slo["apps"].get("chaos") or {}).get(
                "objectives", {}):
            failures.append(f"GET /slo missing chaos objectives: {slo}")
    except (json.JSONDecodeError, KeyError) as e:
        failures.append(f"GET /slo bad payload ({e}): {slo_body!r}")
    scrape_tag = "post-chaos scrape"
    _, ctype, text = _get(base, "/metrics")
    check_scrape(text, ctype, scrape_tag)
    if 'siddhi_diag_bundles_total{app="chaos"}' not in text:
        failures.append(f"{scrape_tag}: recorder families missing")

    httpd.shutdown()
    if failures:
        print(f"FAIL metrics smoke ({len(failures)} violations):")
        for f in failures[:40]:
            print(f"  - {f}")
        return 1
    print(f"metrics smoke OK: {len(mid_scrapes)} mid-traffic scrapes valid, "
          f"{total} events accounted, all always-on families present, "
          "chaos breaker -> bundle -> doctor(3) loop closed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
