#!/usr/bin/env python
"""Cost-model calibration gate (analysis/cost.py vs live telemetry).

For every bench app in tools/fastpath_gate.py's inventory: predict state
bytes and compile-ladder size statically, then build the real runtime,
measure allocated device state (`measure_runtime_state_bytes`) and count
actual warmup compiles, and fail if prediction drifts outside the band
(default 2x either way). This is the CI tripwire that keeps the SL5xx
admission-control math honest — a new operator that allocates state the
model doesn't price shows up here, not as a production OOM.

    python tools/cost_calibrate.py [--json] [--band 2.0]
    python tools/cost_calibrate.py --sweep   # zero-FP: no ERROR-severity
                                             # SL5xx on any known-good app

Exit codes: 0 = calibrated (or sweep clean), 1 = drift outside the band
(or an SL5xx false positive).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from fastpath_gate import APPS  # noqa: E402 — same-dir bench inventory


def _ratio(live: float, predicted: float) -> float:
    if predicted <= 0:
        return 1.0 if live <= 0 else float("inf")
    return live / predicted


def calibrate(band: float) -> tuple[dict, list[str]]:
    from siddhi_tpu.analysis.cost import (compute_cost,
                                          measure_runtime_state_bytes)
    from siddhi_tpu.core.manager import SiddhiManager

    results: dict = {}
    failures: list[str] = []
    mgr = SiddhiManager()
    mgr._lint_enabled = False  # calibration measures, it doesn't gate
    for name, text in APPS.items():
        rep = compute_cost(text)
        if _shard_count(text) > 1:
            # @app:shards prediction is fleet-priced (x n), so the live
            # oracle must measure the REAL plane: build it through a
            # normal manager (the calibration one has plane construction
            # disabled along with the gates) and sum every replica
            pmgr = SiddhiManager()
            plane = pmgr.create_siddhi_app_runtime(text)
            live_bytes = sum(
                sum(measure_runtime_state_bytes(s).values())
                for s in plane.shards)
            plane.warmup()
            live_compiles = sum(
                sum(s.ctx.statistics.compiles.values())
                for s in plane.shards)
            rt = plane
            mgr_of_rt = pmgr
        else:
            rt = mgr.create_siddhi_app_runtime(text)
            live_bytes = sum(measure_runtime_state_bytes(rt).values())
            rt.warmup()
            live_compiles = sum(rt.ctx.statistics.compiles.values())
            mgr_of_rt = mgr
        r_state = _ratio(live_bytes, rep.state_bytes)
        r_comp = _ratio(live_compiles, rep.compile_ladder)
        results[name] = {
            "predicted_state_bytes": rep.state_bytes,
            "live_state_bytes": live_bytes,
            "state_ratio": round(r_state, 4),
            "predicted_compiles": rep.compile_ladder,
            "live_compiles": live_compiles,
            "compile_ratio": round(r_comp, 4),
            "exact": rep.exact,
        }
        for label, r in (("state", r_state), ("compiles", r_comp)):
            if not (1.0 / band <= r <= band):
                failures.append(
                    f"{name}: {label} drifted {r:.3f}x outside "
                    f"[{1.0 / band:.2f}, {band:.2f}]")
        rt.shutdown()
        mgr_of_rt.runtimes.pop(rt.app.name, None)
    return results, failures


def _shard_count(text: str) -> int:
    from siddhi_tpu import compiler
    from siddhi_tpu.analysis.sharding import shard_config
    try:
        cfg = shard_config(compiler.parse(text))
    except Exception:
        return 0
    return 0 if cfg is None else cfg.n


TRIPLE = re.compile(r"(\"\"\"|''')(.*?)\1", re.DOTALL)


def _in_tree_app_strings():
    """Every triple-quoted SiddhiQL-looking string under tests/ + samples/
    (same extraction as tests/test_lint.py's zero-FP sweep), plus the bench
    inventory itself."""
    for name, text in APPS.items():
        yield f"fastpath_gate:{name}", text
    for root in ("tests", "samples"):
        for p in (REPO / root).rglob("*.py"):
            for m in TRIPLE.finditer(p.read_text()):
                s = m.group(2)
                if "define stream" in s and (
                        "insert into" in s or "select" in s):
                    yield str(p), s


def sweep() -> tuple[dict, list[str]]:
    """Zero-false-positive check: no known-good in-tree app may draw an
    ERROR-severity SL5xx finding (budget rules only fire when a budget is
    configured — a clean environment must stay clean)."""
    from siddhi_tpu import compiler
    from siddhi_tpu.analysis import Severity, analyze

    checked = 0
    failures: list[str] = []
    for src, text in _in_tree_app_strings():
        try:
            app = compiler.parse(text)
        except Exception:
            continue  # deliberately-invalid fixtures are out of scope
        try:
            report = analyze(app)
        except Exception:
            continue
        checked += 1
        hits = [d for d in report.diagnostics
                if d.rule_id.startswith("SL5")
                and d.severity is Severity.ERROR]
        for d in hits:
            failures.append(f"{src}: {d.format()}")
    if checked < 25:
        failures.append(f"sweep found too few parseable apps ({checked})")
    return {"apps_checked": checked}, failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--band", type=float, default=2.0,
                    help="allowed live/predicted drift factor (default 2x)")
    ap.add_argument("--sweep", action="store_true",
                    help="run the SL5xx zero-false-positive sweep instead "
                         "of the calibration pass")
    args = ap.parse_args(argv)

    # the gate measures the model, not the operator's shell: a stray budget
    # env would turn predictions into refusals mid-calibration
    for var in ("SIDDHI_STATE_BUDGET", "SIDDHI_COMPILE_BUDGET",
                "SIDDHI_BUDGET_MODE", "SIDDHI_LINT"):
        os.environ.pop(var, None)

    if args.sweep:
        results, failures = sweep()
    else:
        results, failures = calibrate(args.band)

    if args.as_json:
        print(json.dumps({"results": results, "failures": failures},
                         indent=2))
    else:
        if not args.sweep:
            for name, r in results.items():
                print(f"{name}: state {r['live_state_bytes']}/"
                      f"{r['predicted_state_bytes']}B "
                      f"({r['state_ratio']}x), compiles "
                      f"{r['live_compiles']}/{r['predicted_compiles']} "
                      f"({r['compile_ratio']}x)")
        else:
            print(f"sweep: {results['apps_checked']} apps checked")
        for f in failures:
            print(f"DRIFT {f}" if not args.sweep else f"FP {f}")
        print(f"cost calibration: {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
