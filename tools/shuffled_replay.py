#!/usr/bin/env python
"""Shuffled-replay determinism oracle for @app:eventTime (CI gate).

Drives core/upgrade.py shuffled_replay: one event set replayed in
event-time order (the oracle) and in N seed-permuted arrival orders whose
displacement stays inside allowed.lateness, asserting every run's
per-stream output digest is bit-identical to the oracle's with ZERO late
diversions and nothing left buffered after the end-of-stream drain.

Default mode synthesizes a sensor workload — quantized event times (several
readings share a timestamp, as real device fleets do), two queries (an
externalTimeBatch aggregate and a stateless filter) — and journals it
through a real WAL so the arrival list takes the production read path.
Point --app/--wal at your own app + journal to certify a real workload.

    python tools/shuffled_replay.py [--seeds 16] [--events 400]
                                    [--lateness-ms 100] [--json]
    python tools/shuffled_replay.py --app my.siddhi --wal /var/lib/siddhi/wal

Exit codes: 0 = every seed bit-identical, 1 = digest mismatch or a
conservation violation (a late diversion inside the bound, or rows still
buffered after release_watermarks).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from siddhi_tpu import SiddhiManager  # noqa: E402

SYNTH_APP = """
@app:name('DisorderOracle')
@app:eventTime(timestamp='ts', allowed.lateness='{lateness_ms}')
define stream Readings (deviceId long, ts long, temp double);

@info(name='paned')
from Readings#window.externalTimeBatch(ts, 200)
select sum(temp) as total, count() as n
insert into Panes;

@info(name='hot')
from Readings[temp > 50.0]
select deviceId, ts, temp
insert into Hot;
"""


def synth_arrivals(n: int, seed: int = 0):
    """Sensor-fleet workload: event times quantized to 10 ms ticks (so
    several rows share a timestamp), values from a seeded RNG."""
    rng = random.Random(seed)
    base = 1_000_000
    out = []
    for i in range(n):
        ts = base + (i // 3) * 10  # ~3 readings per tick
        out.append(("Readings",
                    ts,
                    (rng.randrange(64), ts, round(rng.uniform(0.0, 99.0), 2))))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--app", help="SiddhiQL file (default: synthetic app)")
    ap.add_argument("--wal", help="WAL directory to replay (default: "
                                  "synthesize events and journal them)")
    ap.add_argument("--seeds", type=int, default=16)
    ap.add_argument("--events", type=int, default=400,
                    help="synthetic event count (ignored with --wal)")
    ap.add_argument("--lateness-ms", type=int, default=100,
                    help="synthetic app's allowed.lateness (ignored w/ --app)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.app:
        app_text = Path(args.app).read_text()
    else:
        app_text = SYNTH_APP.format(lateness_ms=args.lateness_ms)

    mgr = SiddhiManager()
    try:
        if args.wal:
            result = mgr.shuffled_replay(app_text, args.wal,
                                         seeds=args.seeds)
        else:
            # journal the synthetic set through a real WAL so the oracle
            # exercises the production read path end to end
            from siddhi_tpu.compiler import parse
            from siddhi_tpu.state.wal import WriteAheadLog

            app = parse(app_text)
            arrivals = synth_arrivals(args.events)
            with tempfile.TemporaryDirectory() as wal_dir:
                wal = WriteAheadLog(wal_dir, app.name, fsync=False)
                for sid, ts, row in arrivals:
                    wal.append_rows(sid, [ts], [row])
                wal.close()
                result = mgr.shuffled_replay(app, wal_dir, seeds=args.seeds)
    finally:
        mgr.shutdown()

    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(f"shuffled replay: {result['app']!r} — {result['events']} "
              f"events x {result['seeds']} seeds, lateness "
              f"{result['lateness_ms']} ms")
        print(f"  oracle digest {result['oracle_digest'][:16]}… outputs "
              f"{result['outputs']}")
        for r in result["runs"]:
            verdict = "ok" if r["match"] else "MISMATCH"
            print(f"  seed {r['seed']:>2}: {r['digest'][:16]}… "
                  f"({r['permuted']} rows displaced) {verdict}")
        for v in result["violations"]:
            print(f"  VIOLATION: {v}")
        print("PASS: bit-identical across all seeds, zero late diversions"
              if result["matched"] else "FAIL")
    return 0 if result["matched"] else 1


if __name__ == "__main__":
    sys.exit(main())
