#!/usr/bin/env python
"""Fastpath zero-regression gate (SL204) over the in-tree bench apps.

Every compiled step of every bench-suite app is certified against pjit's
C++ dispatch fastpath via `analysis.jaxpr_pass.fastpath_certify`: no host
callback, no ordered effect. Steps listed in KNOWN_VETOED are today's
accepted hit-list (the device-resident-supersteps roadmap item works it
down); everything else must certify, and a previously-clean step turning
vetoed fails CI.

    python tools/fastpath_gate.py [--json]

Exit codes: 0 = no regressions, 1 = a step off the hit-list is vetoed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# one entry per bench config in tools' bench suite (same SiddhiQL texts;
# the bench functions build them inline so they are restated here)
APPS = {
    "filter": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream[700.0 > price]
    select symbol, price
    insert into OutStream;
    """,
    "groupby": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """,
    "distinct": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.time(60 sec)
    select distinctCount(symbol) as distinctSymbols
    insert into OutStream;
    """,
    "pattern": """
    define stream StreamA (val int);
    define stream StreamB (val int);
    @info(name = 'bench')
    from every a=StreamA -> b=StreamB[b.val == a.val] within 5 sec
    select a.val as aVal, b.val as bVal
    insert into OutStream;
    """,
    "join": """
    define stream LeftStream (k int, v double);
    define stream RightStream (k int, v double);
    @info(name = 'bench')
    from LeftStream#window.length(100000) as a
    join RightStream#window.length(100000) as b
    on a.k == b.k
    select a.k as k, a.v as lv, b.v as rv
    insert into OutStream;
    """,
    "disorder": """
    @app:name('Disorder')
    @app:eventTime(timestamp='ts', allowed.lateness='50')
    define stream TradeStream (ts long, v long);
    @info(name = 'bench')
    from TradeStream select ts, v insert into OutStream;
    """,
    "e2e_ingress": """
    @app:name('IngressBench')
    @app:slo(stream='TradeStream', p99.ms='60000')
    @Async(buffer.size='8192', workers='2')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """,
    # the sharded execution plane's bench app (bench.py sharded_e2e): a
    # key-local pipeline — windowless running aggregate grouped by the
    # partition key — replicated per shard behind the partition-key router
    "sharded_e2e": """
    @app:name('ShardedBench')
    @app:shards(n='4', key='symbol')
    @Async(buffer.size='8192', workers='2')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream
    select symbol, sum(price) as total, count() as n
    group by symbol
    insert into SummaryStream;
    """,
}

#: accepted vetoes, keyed "<app>:<step>" — the supersteps hit-list.
#: Adding here requires a written justification next to the entry.
#:
#: _host_radix_argsort: on the CPU backend, group-by/distinct/join steps
#: whose sort width exceeds _RADIX_SORT_MIN_LANES (8192) route through the
#: C radix argsort pure_callback — a measured win over XLA's comparator
#: sort at those widths (ops/search.py) that deliberately trades the
#: fastpath away. The supersteps roadmap item retires these by keeping
#: the sort on-device inside a K-batch lax.scan.
KNOWN_VETOED: dict = {
    "groupby:bench": "_host_radix_argsort above lane threshold (CPU)",
    "distinct:bench": "_host_radix_argsort above lane threshold (CPU)",
    "join:bench/left": "_host_radix_argsort above lane threshold (CPU)",
    "join:bench/right": "_host_radix_argsort above lane threshold (CPU)",
    "e2e_ingress:agg": "_host_radix_argsort above lane threshold (CPU)",
    "sharded_e2e:agg": "_host_radix_argsort above lane threshold (CPU)",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from siddhi_tpu.analysis.jaxpr_pass import fastpath_certify

    results: dict = {}
    regressions = []
    for app_name, text in APPS.items():
        verdicts = fastpath_certify(text)
        if not verdicts:
            regressions.append(f"{app_name}: no steps traced")
        for step, v in verdicts.items():
            key = f"{app_name}:{step}"
            results[key] = v
            if not v["certified"] and key not in KNOWN_VETOED:
                regressions.append(f"{key}: {'; '.join(v['vetoes'])}")
    for key in KNOWN_VETOED:
        if key in results and results[key]["certified"]:
            # hit-list entry went clean: prune it so it can't regress
            print(f"note: {key} is now certified — remove it from "
                  f"KNOWN_VETOED", file=sys.stderr)

    if args.as_json:
        print(json.dumps({"steps": results,
                          "regressions": regressions}, indent=2))
    else:
        n_cert = sum(1 for v in results.values() if v["certified"])
        print(f"fastpath gate: {n_cert}/{len(results)} steps certified, "
              f"{len(regressions)} regression(s)")
        for r in regressions:
            print(f"REGRESSION {r}")
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
