#!/usr/bin/env python
"""Fastpath zero-regression gate (SL204) over the in-tree bench apps.

Every compiled step of every bench-suite app is certified against pjit's
C++ dispatch fastpath via `analysis.jaxpr_pass.fastpath_certify`: no host
callback, no ordered effect. KNOWN_VETOED is EMPTY — the device-resident
supersteps work retired the last hit-list entries (the CPU radix-argsort
pure_callbacks, replaced by the on-device packed-key sort in
ops/search.py) — and the gate is now hard: ANY vetoed step in ANY bench
app fails CI outright. A host callback in a step would also make the
plan superstep-ineligible (core/superstep.py), so this gate doubles as
the superstep-eligibility floor for the bench suite.

    python tools/fastpath_gate.py [--json]

Exit codes: 0 = all steps certified, 1 = any step is vetoed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# one entry per bench config in tools' bench suite (same SiddhiQL texts;
# the bench functions build them inline so they are restated here)
APPS = {
    "filter": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream[700.0 > price]
    select symbol, price
    insert into OutStream;
    """,
    "groupby": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """,
    "distinct": """
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'bench')
    from TradeStream#window.time(60 sec)
    select distinctCount(symbol) as distinctSymbols
    insert into OutStream;
    """,
    "pattern": """
    define stream StreamA (val int);
    define stream StreamB (val int);
    @info(name = 'bench')
    from every a=StreamA -> b=StreamB[b.val == a.val] within 5 sec
    select a.val as aVal, b.val as bVal
    insert into OutStream;
    """,
    "join": """
    define stream LeftStream (k int, v double);
    define stream RightStream (k int, v double);
    @info(name = 'bench')
    from LeftStream#window.length(100000) as a
    join RightStream#window.length(100000) as b
    on a.k == b.k
    select a.k as k, a.v as lv, b.v as rv
    insert into OutStream;
    """,
    "disorder": """
    @app:name('Disorder')
    @app:eventTime(timestamp='ts', allowed.lateness='50')
    define stream TradeStream (ts long, v long);
    @info(name = 'bench')
    from TradeStream select ts, v insert into OutStream;
    """,
    "e2e_ingress": """
    @app:name('IngressBench')
    @app:slo(stream='TradeStream', p99.ms='60000')
    @Async(buffer.size='8192', workers='2')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream#window.lengthBatch(10000)
    select symbol, sum(price) as total, avg(price) as avgPrice
    group by symbol
    insert into SummaryStream;
    """,
    # the sharded execution plane's bench app (bench.py sharded_e2e): a
    # key-local pipeline — windowless running aggregate grouped by the
    # partition key — replicated per shard behind the partition-key router
    "sharded_e2e": """
    @app:name('ShardedBench')
    @app:shards(n='4', key='symbol')
    @Async(buffer.size='8192', workers='2')
    define stream TradeStream (symbol string, price double, volume long);
    @info(name = 'filt')
    from TradeStream[price < 700.0]
    select symbol, price, volume
    insert into MidStream;
    @info(name = 'agg')
    from MidStream
    select symbol, sum(price) as total, count() as n
    group by symbol
    insert into SummaryStream;
    """,
}

#: accepted vetoes, keyed "<app>:<step>". EMPTY by design since the
#: packed-key device sort retired the radix pure_callbacks — adding an
#: entry here requires a written justification next to it, and note that
#: any entry also forfeits superstep eligibility for its plan.
KNOWN_VETOED: dict = {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from siddhi_tpu.analysis.jaxpr_pass import fastpath_certify

    results: dict = {}
    regressions = []
    for app_name, text in APPS.items():
        verdicts = fastpath_certify(text)
        if not verdicts:
            regressions.append(f"{app_name}: no steps traced")
        for step, v in verdicts.items():
            key = f"{app_name}:{step}"
            results[key] = v
            if not v["certified"] and key not in KNOWN_VETOED:
                regressions.append(f"{key}: {'; '.join(v['vetoes'])}")

    if args.as_json:
        print(json.dumps({"steps": results,
                          "regressions": regressions}, indent=2))
    else:
        n_cert = sum(1 for v in results.values() if v["certified"])
        print(f"fastpath gate: {n_cert}/{len(results)} steps certified, "
              f"{len(regressions)} regression(s)")
        for r in regressions:
            print(f"REGRESSION {r}")
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
