"""Bench-trajectory regression gate: diff the newest BENCH_r*.json round
against the previous one, per config.

The repo records one `BENCH_rNN.json` per growth round (written by the
driver around `bench.py`): `tail` holds the run's trailing stdout with one
JSON object per measured config (`{"metric": ..., "value": ...,
"p99_batch_latency_ms": ...}`), and error rounds carry
`{"metric": ..., "error": ...}` instead. Until now nothing read this
trajectory automatically — a 10x throughput cliff between rounds was only
visible to a human diffing JSON by eye.

    python tools/bench_compare.py                 # compare newest vs prev
    python tools/bench_compare.py --threshold 0.5 # fail past 50% regression
    python tools/bench_compare.py --advisory      # print, always exit 0

Per shared metric the table shows events/s and p99 latency deltas. Exit
codes: 0 = within threshold (or nothing comparable), 1 = at least one
metric regressed past --threshold, 2 = usage/IO error. Error entries are
skipped and a round whose configs ALL errored is passed over when picking
the comparison pair — a timeout round must not hide the last real
numbers. CI runs this advisory on CPU runners (shared-runner noise swamps
the signal there); on TPU hosts it is a real gate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: regression ratio that fails the gate: new/old below this for rate
#: metrics (or old/new below it for latency) trips
DEFAULT_THRESHOLD = 0.5

#: preflight fields that are predictions, not measurements — they ride in
#: the round entries (bench.py _preflight) but must never be diffed as if
#: a model change were a perf regression
ADVISORY_FIELDS = frozenset({
    "cost_predicted_state_bytes",
    "cost_predicted_compiles",
    # sharded_e2e's kill-one-host drill: detection/takeover/drain wall
    # times are environment-dependent (subprocess boot, scheduler jitter),
    # reported for trend-watching, never diffed as a regression
    "failover",
    # churn drill sub-metrics: deploy latency is dominated by one XLA
    # retrace (host/compiler dependent), the splice-point ratio is an
    # advisory floor checked in CI, and the counts vary with the Poisson
    # draw — trend data, not regression signals
    "churn_deploy_p50_ms",
    "churn_deploy_p99_ms",
    "churn_splice_throughput_ratio",
    "churn_attaches",
    "churn_detaches",
    "churn_sl501_refused",
    "churn_splices",
})


def parse_round(path: str) -> dict:
    """{metric: entry} for one round file, error entries skipped."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_compare: skipping {path}: {e}", file=sys.stderr)
        return {}
    out: dict = {}
    for line in (data.get("tail") or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            continue
        metric = entry.get("metric")
        if not metric or "error" in entry or "value" not in entry:
            continue
        out[metric] = {k: v for k, v in entry.items()
                       if k not in ADVISORY_FIELDS}
    return out


def collect_rounds(bench_dir: str) -> list[tuple[int, str, dict]]:
    """[(round_no, path, {metric: entry})] sorted oldest→newest, rounds
    with zero parseable configs dropped."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        entries = parse_round(path)
        if entries:
            rounds.append((int(m.group(1)), path, entries))
    rounds.sort()
    return rounds


def compare(old: dict, new: dict, threshold: float) -> tuple[list, list]:
    """(table_rows, regressions) across metrics present in both rounds."""
    rows, regressions = [], []
    for metric in sorted(set(old) & set(new)):
        o, n = old[metric], new[metric]
        ratio = n["value"] / o["value"] if o["value"] else float("inf")
        op99, np99 = (o.get("p99_batch_latency_ms"),
                      n.get("p99_batch_latency_ms"))
        p99_ratio = (np99 / op99 if op99 and np99 else None)
        rows.append((metric, o["value"], n["value"], ratio, op99, np99,
                     p99_ratio))
        if metric.endswith("_ms"):
            # latency-valued metric: lower is better, growing is the
            # regression
            if ratio > 1.0 / threshold:
                regressions.append(
                    f"{metric}: latency grew {ratio:.2f}x "
                    f"({o['value']:.2f} ms -> {n['value']:.2f} ms)")
        elif ratio < threshold:
            regressions.append(
                f"{metric}: events/s fell {ratio:.2f}x "
                f"({o['value']:.0f} -> {n['value']:.0f})")
        if p99_ratio is not None and p99_ratio > 1.0 / threshold:
            regressions.append(
                f"{metric}: p99 grew {p99_ratio:.2f}x "
                f"({op99:.2f} ms -> {np99:.2f} ms)")
    return rows, regressions


def render(rows: list, old_path: str, new_path: str) -> str:
    header = (f"bench_compare: {os.path.basename(old_path)} -> "
              f"{os.path.basename(new_path)}")
    if not rows:
        return header + "\n  (no metric measured in both rounds)"
    lines = [header,
             f"  {'metric':<44} {'old ev/s':>12} {'new ev/s':>12} "
             f"{'ratio':>7} {'old p99':>9} {'new p99':>9}"]
    for metric, ov, nv, ratio, op99, np99, _ in rows:
        lines.append(
            f"  {metric:<44} {ov:>12.0f} {nv:>12.0f} {ratio:>6.2f}x "
            f"{op99 if op99 is not None else float('nan'):>8.2f}m "
            f"{np99 if np99 is not None else float('nan'):>8.2f}m")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Diff the newest bench round against the previous one "
                    "and fail past a regression threshold.")
    p.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="events/s ratio (new/old) below which a metric "
                        f"fails (default {DEFAULT_THRESHOLD}); p99 uses "
                        "the inverse")
    p.add_argument("--advisory", action="store_true",
                   help="print the table but always exit 0 (CPU CI mode)")
    args = p.parse_args(argv)

    rounds = collect_rounds(args.dir)
    if len(rounds) < 2:
        print("bench_compare: fewer than two parseable rounds — "
              "nothing to compare")
        return 0
    (_, old_path, old), (_, new_path, new) = rounds[-2], rounds[-1]
    rows, regressions = compare(old, new, args.threshold)
    print(render(rows, old_path, new_path))
    if regressions:
        for r in regressions:
            print(f"  REGRESSION {r}")
        if args.advisory:
            print("bench_compare: advisory mode — not failing the build")
            return 0
        return 1
    print("bench_compare: within threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
